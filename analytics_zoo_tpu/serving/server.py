"""Cluster Serving — the serving loop, parity with
``serving/ClusterServing.scala:103-134,243-289`` re-designed for a TPU chip:

* the reference runs a Spark-streaming micro-batch per trigger; here one
  background thread drains the input stream and pushes through a jitted
  ``InferenceModel`` (replica-queue concurrency inside),
* requests are batched up to ``batch_size`` per dispatch and padded up
  to a small fixed set of **compiled-shape buckets** (powers of two up
  to ``batch_size`` by default; conf ``zoo.serving.shape_buckets``), so
  ragged traffic reuses a handful of compiled programs instead of
  retracing jit per distinct size — padding rows repeat the last real
  record and are sliced off before publish
  (``zoo_serving_bucket_pad_rows_total``),
* backpressure comes from the bounded stream (``LocalBackend.xadd`` blocks),
  replacing the reference's Redis-memory watermark polling.

The pipeline is organised as **per-model lanes** (the reference's
InferenceModel is explicitly a multi-backend, multi-model runtime —
``InferenceModel.scala:30-67``): one ``ClusterServing`` hosts one or
several named models, records are routed by the optional ``model`` wire
field (absent → the primary lane), and each lane owns its own dispatch
window, pooled batch arenas, AIMD batch target, and **dispatch circuit
breaker** — a model that keeps crashing degrades ITS lane (fast-fail to
addressable errors + DLQ spills once the breaker opens) while the other
lanes keep serving. Admission under overload is **weighted-fair**: when
the shed watermark forces a cut, each lane keeps a share of the
admission window proportional to its configured weight. ``/statusz``
carries a ``models`` block (per-lane batch target, bucket hit-rate,
breaker state) that ``cluster-serving-status`` renders per replica and
as a fleet rollup.

Dispatch is **continuous** (the Orca/Clipper lineage — continuous
batching with per-model adaptive windows): admission is decoupled from
the device step. While any lane has work in flight the loop polls the
stream without blocking, so records that arrive during a device step are
admitted into the *next* dispatch instead of waiting out a read window.
A lane's admitted buffer also carries records ACROSS iterations while
its breaker's half-open probe is in flight (recoverable work waits
instead of shedding) and across a supervised loop restart. The device
only idles when the stream is truly empty.

The host path is pipelined three ways (the Clipper / TF-Serving lineage:
codec and publish work stay off the dispatch critical path):

* **batch arena assembly** — wire-format v2 records (raw little-endian
  bytes + dtype/shape header) are validated cheaply, then a small decode
  worker pool memcpys each record straight into a row of a preallocated,
  pooled batch buffer: no per-record array allocation, no ``np.stack``
  copy. Legacy v1 (base64 ``.npy``) records fall back to a decode-then-
  stack path.
* **dispatch window** — up to ``max_inflight`` batches are dispatched
  with readback deferred (``predict_async``), so a batch's device time +
  round trip overlaps the next batch's read+decode. Default 2 preserves
  the previous two-deep pipeline's memory bound; the permit-deadlock
  handling (flush-oldest before a blocking dispatch) is unchanged.
* **async publisher** — a dedicated thread with a bounded queue performs
  result encode + backend writes (batched via ``set_results``) plus the
  publish-side bookkeeping, so the serve loop never blocks on per-record
  encode or result-store round trips.

The runtime is **self-healing** (``docs/guides/RELIABILITY.md``):

* both loops run under a **supervisor** — an escaped exception restarts
  the loop with bounded backoff (``zoo_serving_loop_restarts_total``),
  and after ``max_loop_restarts`` crashes the server gives up, flipping
  ``/healthz`` to ``down`` with the last traceback on ``/statusz``;
* stream reads are guarded by a **circuit breaker** — transient
  ``ConnectionError``/``OSError`` from the backend is absorbed in-loop,
  consecutive failures open the breaker so a down backend is probed, not
  hammered;
* producers may stamp a ``deadline_ms`` — expired records are answered
  with a distinct ``deadline exceeded`` error before any dispatch;
* a batch whose dispatch crashes is retried **one record at a time**
  (isolating a poison record so its batch-mates still serve); records
  that keep crashing are dead-lettered with an addressable error
  (``zoo_serving_dead_letter_total``) instead of retrying forever.

And it **degrades predictably under sustained overload** instead of
collapsing (RELIABILITY.md "Overload & degradation"):

* **admission control + load shedding** — above a configurable
  stream-depth watermark (``shed_watermark`` /
  ``zoo.serving.shed_watermark``) each read admits the oldest
  ``batch_size`` records and sheds the newest remainder of its admission
  window with a distinct addressable ``shed: server overloaded`` error
  (``zoo_serving_shed_total{reason="depth"}``) — bounding the backlog
  admitted records wait behind, so their latency stays flat while the
  unshedded alternative grows without bound. Deadline-aware admission
  additionally refuses records that *cannot* meet their producer-stamped
  ``deadline_ms`` given the live dispatch-latency estimate
  (``reason="deadline"``) — answering them early costs one error write
  instead of a doomed dispatch. Shedding is degradation, not failure:
  ``/healthz`` stays up and ``/statusz`` carries an ``overload`` block.
* **adaptive batch sizing** — opt-in (``adaptive_batch`` /
  ``zoo.serving.adaptive_batch``): a bounded, deterministic AIMD
  controller grows the per-read batch target toward ``batch_size``
  while the publish backlog and the current read's queue waits stay
  under target, and backs off multiplicatively on a breach
  (``zoo_serving_batch_size_target``).
* **durable dead letters** — with a DLQ attached (``dlq_dir`` /
  ``zoo.serving.dlq_dir``), dispatch-poison records and batches the
  publisher gives up on (after a publisher-side circuit breaker trips)
  spill their full request payload to the append-only on-disk queue in
  ``serving/dlq.py`` — crash-safe, CRC-framed, byte-bounded — and
  ``scripts/zoo-dlq replay`` re-enqueues them after the outage, so a
  result-store outage delays work instead of destroying it.

And it scales HORIZONTALLY as a fleet (docs/guides/SERVING.md,
"Consumer groups & fleet serving"): by default each replica joins the
stream's consumer group under a unique ``consumer_name`` —
``xreadgroup`` delivers every entry to exactly one replica and tracks
it in the group's pending-entries set until the replica ACKS it *after
settlement* (result publish landed, or the record was answered with an
addressable error / shed / dead-lettered — a DLQ spill counts). A
replica that dies between read and publish therefore loses NOTHING: a
survivor's periodic reclaim sweep (``claim_idle_ms``) takes over the
dead peer's pending entries (``zoo_serving_reclaimed_total{from=}``)
and re-serves them; a re-served entry that was in fact already
answered re-answers idempotently (same uri, same value). Replicas
heartbeat depth/pending/utilization into the fleet registry
(``serving/fleet.py``) — producers consult it for coordinated
backpressure, ``start()`` uses it to refuse a mixed-mode fleet (a
legacy consume-on-read server racing a group consumer would
double-serve), and /statusz exposes it as the ``scaling`` block an
autoscaler can act on.
"""

from __future__ import annotations

import collections
import itertools
import logging
import os
import queue
import socket
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common import faults
from ..common.reliability import (AIMDController, CircuitBreaker,
                                  RetryBudget, RetryPolicy)
from ..observability import default_registry, span
from . import fleet as fleet_lib
from .backend import LocalBackend, default_backend
from .client import (INPUT_STREAM, decode_payload, encode_array,
                     encode_tensor, is_v2, validate_v2)
from .dlq import DeadLetterQueue

log = logging.getLogger("analytics_zoo_tpu.serving")

__all__ = ["ClusterServing"]

#: per-request carry-through from stream read to publish: the client's
#: trace id plus the two timestamps later phases diff against. ``t_enq``
#: is WALL epoch seconds (parsed from the ``<epoch_ms>-<seq>`` entry id,
#: the only clock the producer and server share); ``t_deq`` is this
#: process's ``perf_counter`` at read time (monotonic — server-side phase
#: durations must not jump on an NTP step). ``v2`` records the request's
#: wire version so the publisher answers in the same format. ``eid`` is
#: the stream entry id — in consumer-group mode the handle the
#: post-settlement ack needs (None in legacy consume-on-read mode,
#: where the read already consumed the entry).
_Rec = collections.namedtuple("_Rec", ("uri", "trace", "t_enq", "t_deq",
                                       "v2", "eid"))

#: a dispatched batch whose readback is deferred: ``lane`` owns the
#: window and arena pool, ``collect`` blocks on the device transfer,
#: ``arena`` (may be None) returns to the lane's pool after readback
#: proves the device consumed the input buffer. ``recs`` holds the REAL
#: records — the dispatched batch may be bucket-padded past ``len(recs)``
#: and the padding rows are sliced off before publish. ``inputs`` is a
#: DLQ-only copy of the batch's request tensors (None with no DLQ
#: attached) so a publish give-up can spill the original payloads.
_Pending = collections.namedtuple("_Pending", ("lane", "recs", "collect",
                                               "t0", "arena", "inputs"))

#: one admitted record: the record, its raw fields, its queue wait, and
#: its tensor — as a validated v2 (payload, dtype, shape) header (``hdr``)
#: or, for a legacy v1 record, the decoded array (``arr``).
_Item = collections.namedtuple("_Item", ("rec", "fields", "wait", "hdr",
                                         "arr"))

_PUB_STOP = object()    # publisher-queue sentinel: drain, then exit

#: per-process uniquifier for auto-generated consumer names — several
#: in-process replicas (tests, bench) must not collide on hostname+pid
_CONSUMER_SEQ = itertools.count()

#: arena fast-path ceiling: the pool preallocates ``batch_size`` rows
#: from ONE validated header, so a single max-size hostile record would
#: otherwise drive a batch_size-times-larger np.empty (a MemoryError on
#: the unguarded serve loop). Reads whose arena would exceed this
#: assemble via the decode+stack fallback instead, whose allocation is
#: proportional to the bytes actually received off the stream.
_MAX_ARENA_BYTES = 1 << 31

#: per-iteration ceiling on EXTRA entries read just to be shed — sheds
#: are cheap (no decode, batched error writes) but the loop must still
#: touch the stream and the scrape at a bounded cadence under a
#: producer flood; the remaining overage sheds on the next iterations
_SHED_MAX_PER_READ = 256

#: bound on the serve loop's publisher-queue puts: a publisher wedged on
#: a stalled result store must surface as addressable failures (and DLQ
#: spills), not as a serve loop silently parked on an unbounded put
_PUB_PUT_TIMEOUT_S = 30.0

#: deadline-aware admission only engages once this many batches have been
#: dispatched: with fewer observations the dispatch-latency median is
#: dominated by the one-time jit compile (tens of seconds), and refusing
#: deadline-stamped records on it would latch — refused records add no
#: observations, so an inflated cold-start estimate could refuse
#: deadline traffic forever on a server whose steady state is
#: milliseconds. Past the warm-up the compile outlier cannot move the
#: median.
_DOOMED_MIN_OBS = 16

#: the continuous-batching busy poll: while any lane has work in flight
#: the stream read uses this block instead of ``block_ms``, so records
#: arriving during a device step join the NEXT dispatch. 1 ms, not 0 —
#: a 0 means "block forever" to real Redis XREAD.
_BUSY_POLL_MS = 1

#: serving dtype paths a lane may request for a model the SERVER wraps
#: (conf ``zoo.serving.dtype``); pre-built predict models carry their
#: own precision and pass through untouched
_LANE_DTYPES = ("float32", "bfloat16", "bf16", "int8")


def _parse_buckets(spec, batch_size: int):
    """The lane's compiled-shape dispatch buckets: a sorted tuple of
    batch row counts, always topped by ``batch_size`` (a full read must
    fit a bucket). Empty/0/None spec = powers of two up to
    ``batch_size``; a comma-joined string or int sequence names explicit
    buckets. Every bucket must sit in [1, batch_size] — a bucket the
    arena cannot hold would be a silent lie about compile counts."""
    sizes = []
    if spec:
        if isinstance(spec, str):
            sizes = [int(s) for s in spec.split(",") if s.strip()]
        elif isinstance(spec, (list, tuple)):
            sizes = [int(s) for s in spec]
        else:
            raise ValueError(f"shape_buckets must be a comma-joined "
                             f"string or int sequence, got {spec!r}")
        for s in sizes:
            if not 1 <= s <= batch_size:
                raise ValueError(
                    f"shape bucket {s} outside [1, batch_size={batch_size}]")
    if not sizes:
        b = 1
        while b < batch_size:
            sizes.append(b)
            b *= 2
    sizes.append(batch_size)
    return tuple(sorted(set(sizes)))


def _parse_lane_overrides(spec, what: str):
    """Per-lane integer overrides out of a ``"lane:value,lane:value"``
    conf string (or a ``{lane: value}`` mapping) — the
    ``zoo.serving.lane_max_inflight`` / ``zoo.serving.lane_batch_size``
    form. Empty spec = no overrides."""
    if not spec:
        return {}
    if isinstance(spec, dict):
        return {str(k): int(v) for k, v in spec.items()}
    if not isinstance(spec, str):
        raise ValueError(f"{what} must be a 'lane:value' comma-joined "
                         f"string or a mapping, got {spec!r}")
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, val = part.rpartition(":")
        if not sep or not name.strip():
            raise ValueError(f"{what}: entry {part!r} is not 'lane:value'")
        out[name.strip()] = int(val)
    return out


class _ArenaPool:
    """Reusable preallocated batch buffers keyed by (shape, dtype).

    Decode workers write each record's tensor straight into its row, so
    batch assembly costs one memcpy per record — no per-record array
    allocation, no ``np.stack`` copy. A buffer stays checked out for the
    whole dispatch (the device upload reads from it) and is returned by
    the flush after readback. Pooled memory is doubly bounded: at most
    ``cap`` free buffers per key, and at most ``max_bytes`` TOTAL across
    keys (least-recently-used shapes evicted first) — shape-rotating
    traffic must not pin one pool entry per shape forever."""

    def __init__(self, batch_size: int, cap: int = 4,
                 max_bytes: int = None):
        self.batch_size = int(batch_size)
        self.cap = int(cap)
        self.max_bytes = (_MAX_ARENA_BYTES if max_bytes is None
                          else int(max_bytes))
        self._free: "collections.OrderedDict[Tuple, List[np.ndarray]]" \
            = collections.OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()

    def acquire(self, shape: Tuple[int, ...], dtype) -> np.ndarray:
        key = (tuple(shape), np.dtype(dtype).str)
        with self._lock:
            free = self._free.get(key)
            if free:
                arena = free.pop()
                self._bytes -= arena.nbytes
                if free:
                    self._free.move_to_end(key)
                else:
                    del self._free[key]
                return arena
        return np.empty((self.batch_size,) + tuple(shape), np.dtype(dtype))

    def release(self, arena: Optional[np.ndarray]) -> None:
        if arena is None:
            return
        key = (arena.shape[1:], arena.dtype.str)
        with self._lock:
            free = self._free.setdefault(key, [])
            if len(free) >= self.cap:
                return
            free.append(arena)
            self._bytes += arena.nbytes
            self._free.move_to_end(key)
            while self._bytes > self.max_bytes:
                k, lst = next(iter(self._free.items()))
                self._bytes -= lst.pop().nbytes
                if not lst:
                    del self._free[k]


class _Lane:
    """One model's serving lane — the per-model half of the pipeline
    state the serve loop multiplexes: the admitted-record buffer
    (records read off the stream, waiting for their next device step),
    the dispatch window (``pendings``), pooled batch arenas, the AIMD
    batch target, the dispatch circuit breaker (a model that keeps
    crashing fast-fails ITS records without stalling the other lanes),
    and the per-model accounting behind ``/statusz``'s ``models``
    block. Records are routed here by the ``model`` wire field; the
    primary (first-configured) lane takes unlabeled records."""

    def __init__(self, name: str, model, weight: float, dtype: str,
                 buckets, batch_size: int, max_inflight: int,
                 batch_ctl: Optional[AIMDController],
                 breaker: Optional[CircuitBreaker], metrics,
                 initial_target: int):
        self.name = name
        self.model = model
        self.weight = float(weight)
        if self.weight <= 0:
            raise ValueError(f"lane {name!r}: admission weight must be > 0")
        self.dtype = dtype or "float32"
        #: per-lane ceilings (zoo.serving.lane_batch_size /
        #: lane_max_inflight, or lane-spec entries): a big model's lane
        #: caps its own dispatch size and window so its device time and
        #: arena memory can't starve the small models multiplexed next
        #: to it — the shared serve loop interleaves lanes per read, so
        #: without a cap one lane's batch_size-deep dispatches monopolize
        #: the device between polls
        self.batch_size = int(batch_size)
        if self.batch_size < 1:
            raise ValueError(f"lane {name!r}: batch_size must be >= 1")
        self.max_inflight = max(int(max_inflight), 1)
        # the shared bucket ladder capped to this lane's ceiling (dedup
        # keeps the compile count at most the shared ladder's)
        self.buckets = tuple(sorted({min(b, self.batch_size)
                                     for b in buckets}))
        self.pendings: "collections.deque[_Pending]" = collections.deque()
        self.buffer: "collections.deque[_Item]" = collections.deque()
        self.arena_pool = _ArenaPool(self.batch_size,
                                     cap=self.max_inflight + 2)
        self.batch_ctl = batch_ctl if batch_ctl is not None \
            else AIMDController(floor=1, ceiling=self.batch_size)
        #: guards THIS model's dispatches: consecutive crashes open it
        #: and the lane fast-fails (addressable error + DLQ spill)
        #: instead of burning the shared loop on a dead model; the
        #: half-open probe dispatches one real batch. The default
        #: threshold sits above the poison-isolation retry budget so a
        #: single poison batch never trips a healthy model's lane.
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            name=f"serving.dispatch.{name}", failure_threshold=16,
            reset_timeout=2.0, registry=metrics)
        self.last_read_waits: List[float] = []
        self.dispatches = 0      # assembled batches (bucket hit-rate base)
        self.bucket_hits = 0     # assembled with no padding rows
        labels = {"model": name}
        # model = the configured lane set, bounded by deployment config
        self.m_records = metrics.counter(  # zoolint: disable=ZL015 bounded label set
            "zoo_serving_model_records_total",
            "records answered with a prediction, per model lane",
            labels=labels)
        self.m_dispatches = metrics.counter(  # zoolint: disable=ZL015 bounded label set
            "zoo_serving_model_dispatches_total",
            "batches dispatched to the device, per model lane",
            labels=labels)
        self.m_pad_rows = metrics.counter(  # zoolint: disable=ZL015 bounded label set
            "zoo_serving_bucket_pad_rows_total",
            "padding rows added to reach a compiled bucket shape "
            "(sliced off before publish, never answered), per model lane",
            labels=labels)
        self.m_target = metrics.gauge(  # zoolint: disable=ZL015 bounded label set
            "zoo_serving_model_batch_target",
            "per-model adaptive batch target (AIMD; equals batch_size "
            "when adaptive_batch is off)", labels=labels)
        self.m_target.set(initial_target)

    def bucket_for(self, k: int) -> int:
        """The smallest compiled-shape bucket holding ``k`` rows."""
        for b in self.buckets:
            if b >= k:
                return b
        return self.buckets[-1]

    def bucket_hit_rate(self) -> Optional[float]:
        """Fraction of assembled batches that needed no padding rows;
        None before the first dispatch."""
        if not self.dispatches:
            return None
        return self.bucket_hits / self.dispatches


class ClusterServing:
    """Owns the serve loop: xread → batched predict → result writes.

    Observability (``docs/guides/OBSERVABILITY.md``): every batch updates
    the ``zoo_serving_*`` metrics in ``registry`` (default: the
    process-wide one) — records/batches/error counters, stream-depth and
    publish-backlog gauges, batch-size, queue-wait, codec
    (decode/encode) and dispatch→publish latency histograms plus
    p50/p95/p99 quantile summaries (queue-wait, dispatch, and
    end-to-end) — scrapeable via :meth:`serve_metrics`, which also mounts
    ``/healthz`` and ``/statusz``; :meth:`set_json_events` additionally
    logs one structured JSON event per flush/error and, for every record
    the client stamped with a trace id, parent-linked per-request phase
    events (enqueue→dequeue→dispatch→publish) under that id."""

    def __init__(self, model, backend: Optional[LocalBackend] = None,
                 batch_size: int = 32, stream: str = INPUT_STREAM,
                 block_ms: int = 50, registry=None, decode_workers: int = 2,
                 max_inflight: int = 2, publish_queue: int = 8,
                 max_loop_restarts: int = 5,
                 restart_backoff: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 dispatch_retries: int = 1,
                 retry_budget: Optional[RetryBudget] = None,
                 shed_watermark: Optional[int] = None,
                 adaptive_batch: Optional[bool] = None,
                 queue_wait_target_s: Optional[float] = None,
                 batch_controller: Optional[AIMDController] = None,
                 weights: Optional[Dict[str, float]] = None,
                 dtype: Optional[str] = None,
                 shape_buckets=None,
                 dispatch_breakers: Optional[Dict[str,
                                                  CircuitBreaker]] = None,
                 publish_breaker: Optional[CircuitBreaker] = None,
                 dlq: Optional[DeadLetterQueue] = None,
                 dlq_dir: Optional[str] = None,
                 consumer_group: Optional[str] = None,
                 consumer_name: Optional[str] = None,
                 claim_idle_ms: Optional[float] = None,
                 claim_sweep_s: Optional[float] = None,
                 max_deliveries: Optional[int] = None,
                 heartbeat_s: float = 1.0,
                 fleet_ttl_s: float = fleet_lib.DEFAULT_TTL_S):
        #: one model (any ``.predict(x)``) or ``{name: model-or-spec}``
        #: for a multiplexed server — normalized into lanes below, after
        #: the shared knobs/metrics they hang off exist
        self._model_spec = model
        self.backend = backend if backend is not None else default_backend()
        self.batch_size = int(batch_size)
        self.stream = stream
        self.block_ms = int(block_ms)
        #: decode worker threads for batch assembly (0 = decode inline on
        #: the serve loop); v1 base64+.npy decodes and large-arena memcpys
        #: release the GIL, so a small pool overlaps them
        self.decode_workers = max(int(decode_workers), 0)
        #: dispatched-but-unpublished batch window; 2 = the previous
        #: two-deep pipeline's memory bound (one in flight, one being
        #: assembled)
        self.max_inflight = max(int(max_inflight), 1)
        self._pub_maxsize = max(int(publish_queue), 1)
        self._pub_queue: Optional["queue.Queue"] = None
        self._pub_thread: Optional[threading.Thread] = None
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_lock = threading.Lock()
        self._pool: Optional[ThreadPoolExecutor] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.served = 0             # this server's records (tests/ops; the
        #                             registry counters are process-cumulative)
        self._summary = None        # InferenceSummary role (TB scalars)
        self._batches = 0
        self._t_last_flush = None   # throughput-interval anchor
        self.metrics = registry if registry is not None else default_registry()
        m = self.metrics
        self._m_records = m.counter(
            "zoo_serving_records_total", "records answered with a prediction")
        self._m_batches = m.counter(
            "zoo_serving_batches_total", "batches published")
        self._m_undecodable = m.counter(
            "zoo_serving_undecodable_total",
            "records dropped with an undecodable-payload error")
        self._m_failures = m.counter(
            "zoo_serving_failures_total",
            "records answered with a failure error, all kinds (see "
            "zoo_serving_failure_errors_total for the breakdown)")
        self._m_depth = m.gauge(
            "zoo_serving_stream_depth", "input-stream backlog after a read")
        self._m_backlog = m.gauge(
            "zoo_serving_publish_backlog",
            "batches queued for the async publisher (encode + result "
            "writes pending)")
        self._m_batch_size = m.histogram(
            "zoo_serving_batch_size", "records per published batch")
        self._m_queue_wait = m.histogram(
            "zoo_serving_queue_wait_seconds",
            "enqueue to read-off-the-stream wait per record")
        self._m_decode = m.histogram(
            "zoo_serving_decode_seconds",
            "payload decode + batch assembly wall time per read "
            "(across all decode workers)")
        self._m_encode = m.histogram(
            "zoo_serving_encode_seconds",
            "result encode wall time per published batch (publisher "
            "thread)")
        self._m_dispatch = m.histogram(
            "zoo_serving_dispatch_seconds",
            "dispatch to publish latency per batch")
        self._m_skew = m.counter(
            "zoo_serving_clock_skew_total",
            "queue-wait observations clamped to zero because the client "
            "clock ran ahead of the server's")
        # quantile digests alongside the histograms: the octave buckets
        # keep the shape, the summaries answer "what IS p99" exactly
        # enough to hold an SLO against (and merge across replicas)
        self._q_queue_wait = m.summary(
            "zoo_serving_queue_wait_quantiles_seconds",
            "queue-wait p50/p95/p99 per record (quantile digest)")
        self._q_dispatch = m.summary(
            "zoo_serving_dispatch_quantiles_seconds",
            "dispatch to publish p50/p95/p99 per batch (quantile digest)")
        self._q_e2e = m.summary(
            "zoo_serving_e2e_quantiles_seconds",
            "enqueue to publish end-to-end p50/p95/p99 per record "
            "(quantile digest)")
        self._last_flush_wall = None   # epoch s of the newest publish
        self._events = None         # JsonEventSink (set_json_events)
        self._scrape = None         # ScrapeServer (serve_metrics)
        self._profiler = None       # ProfilerTrigger (serve_metrics)
        # -- reliability (docs/guides/RELIABILITY.md) -----------------------
        #: crashes each supervised loop survives per start() before the
        #: supervisor gives up and /healthz reads down
        self.max_loop_restarts = max(int(max_loop_restarts), 0)
        #: backoff between restarts (its delays stretch restart storms;
        #: the restart COUNT bound is max_loop_restarts)
        self._restart_policy = restart_backoff if restart_backoff \
            is not None else RetryPolicy(
                max_attempts=self.max_loop_restarts + 1,
                base_delay=0.05, max_delay=1.0)
        #: guards the loop's backend reads: consecutive transport failures
        #: open it, so a down backend gets probes, not a poll storm
        self._breaker = breaker if breaker is not None else CircuitBreaker(
            name="serving.backend", failure_threshold=3, reset_timeout=1.0,
            registry=m)
        #: solo re-dispatch attempts per record after its batch crashed
        #: (0 = fail the whole batch immediately, the pre-reliability
        #: behavior); beyond this the record is dead-lettered
        self.dispatch_retries = max(int(dispatch_retries), 0)
        #: optional SHARED RetryBudget (docs/guides/RELIABILITY.md):
        #: solo re-dispatches withdraw from it and successful dispatches
        #: deposit, so a fleet of replicas against one broken model/backend
        #: cannot multiply retries during a correlated outage
        self._retry_budget = retry_budget
        self._m_restarts = {
            name: m.counter(
                "zoo_serving_loop_restarts_total",
                "supervised loop restarts after an escaped exception",
                labels={"loop": name})
            for name in ("serve", "publish")}
        self._m_deadline = m.counter(
            "zoo_serving_deadline_exceeded_total",
            "records answered with a deadline-exceeded error before "
            "dispatch")
        self._m_dead_letter = m.counter(
            "zoo_serving_dead_letter_total",
            "records dead-lettered after repeated dispatch crashes")
        self._crash_info: Dict[str, str] = {}   # loop -> last traceback
        self._loop_down: set = set()            # loops whose supervisor gave up
        # -- overload / degradation (RELIABILITY.md "Overload & degradation")
        #: stream-depth watermark: >0 sheds the newest remainder of each
        #: admission window once the backlog exceeds it (0 = off)
        self.shed_watermark = int(self._conf("zoo.serving.shed_watermark", 0)
                                  if shed_watermark is None
                                  else shed_watermark)
        self._m_shed = {
            reason: m.counter(
                "zoo_serving_shed_total",
                "records shed by admission control, by reason: depth = "
                "backlog above the watermark, deadline = cannot meet its "
                "producer-stamped deadline",
                labels={"reason": reason})
            for reason in ("depth", "deadline")}
        # -- goodput attribution (docs/guides/OBSERVABILITY.md "Goodput
        # & performance attribution"): the lane loop notes the
        # read/shed/route/pump seams so every second of this replica's
        # wall clock lands in exactly one category
        if bool(self._conf("zoo.goodput.enabled", True)):
            from ..observability.goodput import GoodputLedger
            self._goodput = GoodputLedger("serve", registry=m)
        else:
            self._goodput = None
        #: AIMD batch-size control, off by default — `batch_size` is the
        #: ceiling, the live backlog/queue-wait signals drive the target
        self.adaptive_batch = bool(
            self._conf("zoo.serving.adaptive_batch", False)
            if adaptive_batch is None else adaptive_batch)
        self.queue_wait_target_s = float(
            self._conf("zoo.serving.queue_wait_target_ms", 500) / 1000.0
            if queue_wait_target_s is None else queue_wait_target_s)
        # -- per-model lanes (multiplexing; docs/guides/SERVING.md) ---------
        #: serving dtype path for models the server wraps (KerasNet lane
        #: specs go through InferenceModel on this precision; conf
        #: ``zoo.serving.dtype``); prebuilt predict models pass through
        self.dtype = str(self._conf("zoo.serving.dtype", "float32")
                         if dtype is None else dtype)
        if self.dtype not in _LANE_DTYPES:
            raise ValueError(f"unknown serving dtype {self.dtype!r}; "
                             f"use one of {_LANE_DTYPES}")
        #: compiled-shape dispatch buckets shared by every lane (conf
        #: ``zoo.serving.shape_buckets``; "" = powers of two)
        self.shape_buckets = _parse_buckets(
            self._conf("zoo.serving.shape_buckets", "")
            if shape_buckets is None else shape_buckets, self.batch_size)
        specs = (self._model_spec if isinstance(self._model_spec, dict)
                 else {"default": self._model_spec})
        if not specs:
            raise ValueError("ClusterServing needs at least one model")
        weights = dict(weights or {})
        dispatch_breakers = dict(dispatch_breakers or {})
        for param, keys in (("weights", weights),
                            ("dispatch_breakers", dispatch_breakers)):
            unknown = set(keys) - {str(n) for n in specs}
            if unknown:
                # a typo here would silently flatten the operator's
                # intended admission ratio (or substitute the default
                # breaker) — refuse loudly instead
                raise ValueError(
                    f"{param} names unknown lane(s) {sorted(unknown)}; "
                    f"configured lanes: {sorted(str(n) for n in specs)}")
        # per-lane ceilings (mixed model sizes): a big model's lane can
        # cap its own dispatch size / in-flight window below the shared
        # defaults so it cannot starve the other lanes' device time or
        # arena memory. Conf overrides win over lane-spec entries
        # (operator knob beats deployment code); both sit under the
        # server-wide values, which remain the ceilings' ceiling.
        lane_inflight = _parse_lane_overrides(
            self._conf("zoo.serving.lane_max_inflight", ""),
            "zoo.serving.lane_max_inflight")
        lane_batch = _parse_lane_overrides(
            self._conf("zoo.serving.lane_batch_size", ""),
            "zoo.serving.lane_batch_size")
        for key, overrides in (
                ("zoo.serving.lane_max_inflight", lane_inflight),
                ("zoo.serving.lane_batch_size", lane_batch)):
            unknown = set(overrides) - {str(n) for n in specs}
            if unknown:
                # conf is process-global (other servers may own those
                # lanes) — warn, don't refuse
                log.warning("%s names lane(s) %s not configured on this "
                            "server (lanes: %s) — ignored here", key,
                            sorted(unknown),
                            sorted(str(n) for n in specs))
        self._lanes: "collections.OrderedDict[str, _Lane]" = \
            collections.OrderedDict()
        for i, (name, spec) in enumerate(specs.items()):
            name = str(name)
            if not name:
                raise ValueError("lane names must be non-empty strings")
            opts = dict(spec) if isinstance(spec, dict) else {"model": spec}
            if "model" not in opts:
                raise ValueError(f"lane {name!r}: spec dict needs a "
                                 f"'model' entry")
            lane_dtype = str(opts.get("dtype") or self.dtype)
            if lane_dtype not in _LANE_DTYPES:
                raise ValueError(f"lane {name!r}: unknown dtype "
                                 f"{lane_dtype!r}; use one of {_LANE_DTYPES}")
            lane_bs = int(lane_batch.get(
                name, opts.get("batch_size", self.batch_size)))
            lane_mi = int(lane_inflight.get(
                name, opts.get("max_inflight", self.max_inflight)))
            if not 1 <= lane_bs <= self.batch_size:
                raise ValueError(
                    f"lane {name!r}: batch_size ceiling {lane_bs} outside "
                    f"[1, batch_size={self.batch_size}]")
            if not 1 <= lane_mi <= self.max_inflight:
                raise ValueError(
                    f"lane {name!r}: max_inflight {lane_mi} outside "
                    f"[1, max_inflight={self.max_inflight}]")
            self._lanes[name] = _Lane(
                name=name,
                model=self._wrap_model(opts["model"], lane_dtype),
                weight=weights.get(name, opts.get("weight", 1.0)),
                dtype=lane_dtype,
                buckets=self.shape_buckets,
                batch_size=lane_bs,
                max_inflight=lane_mi,
                # the ctor's batch_controller names the PRIMARY lane's
                # controller (single-model back-compat)
                batch_ctl=(batch_controller if i == 0 else None),
                breaker=dispatch_breakers.get(name),
                metrics=m,
                initial_target=lane_bs)
        #: the primary lane: first configured — takes records without a
        #: ``model`` wire field, and backs the single-model aliases
        self._primary = next(iter(self._lanes))
        primary = self._lanes[self._primary]
        self.model = primary.model          # single-model back-compat
        self._batch_ctl = primary.batch_ctl
        self._arena_pool = primary.arena_pool
        self._m_batch_target = m.gauge(
            "zoo_serving_batch_size_target",
            "adaptive per-read batch target of the primary lane (AIMD; "
            "equals batch_size when adaptive_batch is off; per-lane "
            "targets in zoo_serving_model_batch_target)")
        init_target = (self._batch_ctl.value if self.adaptive_batch
                       else self.batch_size)
        self._m_batch_target.set(init_target)
        for lane in self._lanes.values():
            lane.m_target.set(lane.batch_ctl.value if self.adaptive_batch
                              else self.batch_size)
        #: guards publisher writes: repeated publish failures trip it so
        #: an outage fast-fails to the DLQ instead of burning the publish
        #: queue's drain time on a dead result store
        self._pub_breaker = publish_breaker if publish_breaker is not None \
            else CircuitBreaker(name="serving.publish", failure_threshold=3,
                                reset_timeout=5.0, registry=m)
        #: durable dead letters: records serving gives up on spill here
        #: (dispatch poison, publish give-up) for operator replay
        if dlq is not None:
            self._dlq: Optional[DeadLetterQueue] = dlq
        else:
            if dlq_dir is None:
                dlq_dir = str(self._conf("zoo.serving.dlq_dir", "") or "")
            self._dlq = DeadLetterQueue(
                dlq_dir,
                max_bytes=int(self._conf("zoo.serving.dlq_max_bytes",
                                         64 << 20)),
                registry=m) if dlq_dir else None
        # -- consumer groups / fleet (docs/guides/SERVING.md) ---------------
        #: the group this replica consumes under; "" = legacy single-
        #: consumer consume-on-read (the pre-fleet wire behavior)
        if consumer_group is None:
            consumer_group = str(self._conf("zoo.serving.consumer_group",
                                            "serving"))
        self.consumer_group = consumer_group
        #: group mode needs the backend's group surface; a foreign
        #: minimal backend falls back to legacy mode with a log line
        self._group_mode = bool(consumer_group) and all(
            hasattr(self.backend, meth)
            for meth in ("xgroup_create", "xreadgroup", "xack",
                         "xautoclaim"))
        if consumer_group and not self._group_mode:
            log.info("backend %s has no consumer-group surface; serving "
                     "in legacy single-consumer mode",
                     type(self.backend).__name__)
        #: this replica's identity in the group AND the fleet registry —
        #: stable across supervisor restarts (the same identity re-claims
        #: its own pending entries), unique across replicas by default
        self.consumer_name = consumer_name if consumer_name else (
            f"{socket.gethostname()}-{os.getpid()}-"
            f"{next(_CONSUMER_SEQ)}")
        #: pending entries idle past this are reclaimable by a survivor
        self.claim_idle_ms = float(
            self._conf("zoo.serving.claim_idle_ms", 30000)
            if claim_idle_ms is None else claim_idle_ms)
        if self.claim_idle_ms <= 0:
            raise ValueError("claim_idle_ms must be > 0")
        #: how often this replica sweeps for reclaimable entries —
        #: default half the idle threshold, so a dead peer's entries
        #: wait at most ~1.5x claim_idle_ms before a survivor takes over
        self.claim_sweep_s = float(
            max(self.claim_idle_ms / 2000.0, 0.01)
            if claim_sweep_s is None else claim_sweep_s)
        #: an entry delivered (read + reclaims) more than this many
        #: times is poison hopping replica to replica: dead-letter it
        #: addressably instead of reclaiming it forever
        self.max_deliveries = int(
            self._conf("zoo.serving.max_deliveries", 5)
            if max_deliveries is None else max_deliveries)
        self.heartbeat_s = float(heartbeat_s)
        self.fleet_ttl_s = float(fleet_ttl_s)
        self._m_acks = m.counter(
            "zoo_serving_acks_total",
            "stream entries acked (settled) out of the consumer group's "
            "pending-entries set")
        self._m_pending = m.gauge(
            "zoo_serving_pending_entries",
            "entries delivered to THIS consumer and not yet acked")
        self._m_util = m.gauge(
            "zoo_serving_utilization",
            "busy-dispatch fraction of the serve loop between heartbeats "
            "(0 = idle poll, 1 = saturated) — the autoscaler signal")
        self._last_sweep = 0.0
        self._last_hb = 0.0
        self._busy_s = 0.0
        self._util_anchors: Dict[str, Tuple[float, float]] = {}
        self._killed = False

    @property
    def _mode(self) -> str:
        """The fleet-registry mode string the mixed-version guard
        compares: ``group:<name>`` or ``single``."""
        return (f"group:{self.consumer_group}" if self._group_mode
                else "single")

    @staticmethod
    def _conf(key: str, default):
        """A zoo-context conf read, imported lazily — constructing a
        server must not pull the jax-backed context module in unless a
        knob actually defaults from it."""
        from ..common.context import get_zoo_context
        return get_zoo_context().get(key, default)

    @staticmethod
    def _wrap_model(model, dtype: str):
        """The int8/bf16 serving wiring: a lane spec naming a bare
        KerasNet (``.apply``/``.params``, no predict surface) is wrapped
        in an ``InferenceModel`` on the lane's dtype path —
        ``dtype="int8"`` loads the existing int8 weight-only inference
        path (int8 weights in HBM, fp32 activations AND fp32 results on
        the wire). Anything already exposing ``.predict_async`` (an
        ``InferenceModel``, or any custom async model) carries its own
        precision and passes through untouched, as does any foreign
        ``.predict`` object without the KerasNet surface. Imported
        lazily: only a KerasNet spec pulls jax in."""
        if hasattr(model, "predict_async"):
            return model
        if hasattr(model, "apply") and hasattr(model, "params"):
            from ..pipeline.inference import InferenceModel
            im = InferenceModel(concurrent_num=2)
            if dtype == "int8":
                return im.from_keras(model, quantize="int8")
            if dtype in ("bfloat16", "bf16"):
                return im.from_keras(model, dtype="bfloat16")
            return im.from_keras(model)
        return model

    def _lane_target(self, lane: _Lane) -> int:
        """The lane's current per-dispatch batch target, capped by its
        batch-size ceiling (the primary lane's injected controller may
        carry a wider ceiling)."""
        target = (lane.batch_ctl.value if self.adaptive_batch
                  else self.batch_size)
        return min(target, lane.batch_size)

    def _lane_name(self, fields) -> Optional[str]:
        """Route one record's ``model`` wire field to a lane name; no
        field → the primary lane; an unconfigured name → None (answered
        with the addressable ``unknown model`` error, never dispatched)."""
        name = fields.get("model")
        if not name:
            return self._primary
        name = str(name)
        return name if name in self._lanes else None

    def set_tensorboard(self, log_dir: str,
                        app_name: str = "serving") -> "ClusterServing":
        """Write per-batch "Serving Throughput" / "Serving Records" scalars
        (the reference's throughput-to-TensorBoard path,
        ``ClusterServing.scala:291-317`` + ``InferenceSummary.scala``).
        Call before ``start()`` — swapping the writer under a running
        publisher would race its bookkeeping on the closed file handle."""
        import os
        from ..utils.tensorboard import EventFileWriter
        if self._thread is not None:    # mirrors start()'s double-start guard
            raise RuntimeError(
                "serving already started; call set_tensorboard() before "
                "start() (or after stop())")
        if self._summary is not None:  # redirecting: release the old fd
            self._summary.close()
        self._summary = EventFileWriter(os.path.join(log_dir, app_name))
        return self

    def set_json_events(self, path: str) -> "ClusterServing":
        """Log one structured JSON event per published batch / error record
        to ``path`` (JSON lines; see OBSERVABILITY.md). The sink is also
        attached to this server's registry, so spans emit there too. Call
        before ``start()``."""
        from ..observability import JsonEventSink
        if self._thread is not None:
            raise RuntimeError(
                "serving already started; call set_json_events() before "
                "start() (or after stop())")
        if self._events is not None:
            self.metrics.remove_event_sink(self._events)
            self._events.close()
        self._events = JsonEventSink(path)
        self.metrics.add_event_sink(self._events)
        return self

    def serve_metrics(self, port: int = 0, host: str = "127.0.0.1"):
        """Mount the observability HTTP endpoint over this server's
        registry — ``/metrics`` (Prometheus exposition), ``/healthz``
        (liveness + serve-loop state), ``/statusz`` (operator page:
        uptime, stream depth, last-flush age, jit-compile totals,
        device info, the goodput ``performance`` block) and ``POST
        /profilez`` (arm a bounded profiler capture on this replica).
        Returns the :class:`ScrapeServer` (bound port on
        ``.port``); closed automatically by :meth:`stop`. Pretty-print
        it from a shell with ``scripts/cluster-serving-status``.
        ``host="0.0.0.0"`` exposes it to an off-host Prometheus scraper
        (the default binds loopback only)."""
        from ..observability import ProfilerTrigger, ScrapeServer
        if self._scrape is not None:
            self._scrape.close()
        if self._profiler is None:
            self._profiler = ProfilerTrigger(registry=self.metrics)
        self._scrape = ScrapeServer(self.metrics, port=port, host=host,
                                    health_fn=self._health_info,
                                    profiler=self._profiler)
        return self._scrape

    def _health_info(self) -> dict:
        """Serve-loop introspection for /healthz and /statusz. Runs on
        the scrape thread — reads only cheap fields and the backend's
        stream length (its lock is held per operation, never across a
        dispatch). A loop whose supervisor gave up flips the whole
        payload's ``status`` to ``down``, with the last traceback
        included (what /statusz shows an operator first)."""
        age = (None if self._last_flush_wall is None
               else max(time.time() - self._last_flush_wall, 0.0))
        thread = self._thread
        pub = self._pub_queue
        try:
            # same backlog semantics as _stream_depth/_heartbeat: on
            # real Redis XLEN counts every replica's delivered-but-
            # unacked entries, which would double-count the separately
            # reported pending_entries and tell an autoscaler an idle
            # fleet is backed up
            if self._group_mode and hasattr(self.backend, "backlog_len"):
                depth = self.backend.backlog_len(self.stream,
                                                 self.consumer_group)
            else:
                depth = self.backend.stream_len(self.stream)
        except Exception as e:      # a dead backend must not 500 /healthz
            depth = None
            log.debug("stream_len failed on the scrape thread: %s", e)
        down = sorted(self._loop_down)
        info = {"serving": {
            # is_alive AND not given-up: a serve loop killed by an
            # escaped exception must read as down — a liveness endpoint
            # that says ok over a dead loop is worse than none
            "running": (thread is not None and thread.is_alive()
                        and "serve" not in self._loop_down),
            "stream_depth": depth,
            "served": self.served,
            "batches": self._batches,
            "publish_backlog": 0 if pub is None else pub.qsize(),
            "last_flush_age_s": age,
            "backend_breaker": self._breaker.state,
            "loops_down": down,
        }}
        # degradation is NOT failure: shedding/DLQ activity shows up here
        # (and in the scrape) while "status" stays up — an overloaded
        # server that answers what it admits must not get itself
        # restarted by a liveness probe
        overload = {
            "stream_depth": depth,
            "shed_watermark": self.shed_watermark,
            "shed_depth_total": self._m_shed["depth"].value,
            "shed_deadline_total": self._m_shed["deadline"].value,
            "adaptive_batch": self.adaptive_batch,
            "batch_size_target": (self._batch_ctl.value
                                  if self.adaptive_batch
                                  else self.batch_size),
            "publish_breaker": self._pub_breaker.state,
        }
        if self._dlq is not None:
            overload["dlq_records"] = self._dlq._m_records.value
            overload["dlq_bytes"] = self._dlq._m_bytes.value
        info["serving"]["overload"] = overload
        # the scaling block: what an autoscaler reads off /statusz —
        # per-replica backlog, in-flight pending entries, and the
        # busy-dispatch fraction since the last scrape
        info["serving"]["scaling"] = {
            "consumer": self.consumer_name,
            "group": self.consumer_group if self._group_mode else None,
            "stream_depth": depth,
            "pending_entries": self._own_pending(),
            "utilization": round(self._utilization("health"), 4),
            "batch_size_target": overload["batch_size_target"],
            "goodput": (None if self._goodput is None
                        or self._goodput.wall() <= 0
                        else round(self._goodput.ratio(), 4)),
        }
        # the models block: one row per lane — what the status CLI
        # renders per replica and rolls up fleet-wide. Reads are cheap
        # snapshot fields (counters, deque lengths, breaker state); the
        # scrape thread never touches a dispatch.
        models = {}
        for name, lane in self._lanes.items():
            hit = lane.bucket_hit_rate()
            models[name] = {
                "weight": lane.weight,
                "dtype": lane.dtype,
                "batch_target": self._lane_target(lane),
                "batch_ceiling": lane.batch_size,
                "max_inflight": lane.max_inflight,
                "buckets": list(lane.buckets),
                "bucket_hit_rate": None if hit is None else round(hit, 4),
                "breaker": lane.breaker.state,
                "records": lane.m_records.value,
                "pad_rows": lane.m_pad_rows.value,
                "buffered": len(lane.buffer),
                "inflight": len(lane.pendings),
            }
        info["serving"]["models"] = models
        if self._crash_info:
            info["serving"]["last_crash"] = dict(self._crash_info)
        if down:
            info["status"] = "down"
        return info

    def _own_pending(self) -> Optional[int]:
        """THIS consumer's pending-entry count (delivered, unacked);
        None in legacy mode or when the backend cannot answer."""
        if not self._group_mode:
            return None
        try:
            return int(self.backend.xpending(
                self.stream, self.consumer_group).get(self.consumer_name, 0))
        except Exception as e:
            log.debug("xpending failed: %s", e)
            return None

    def _utilization(self, anchor: str) -> float:
        """Busy-dispatch fraction of the serve loop since THIS anchor's
        last reading (each consumer of the signal — /statusz scrapes,
        fleet heartbeats — gets its own window). The loop accumulates
        ``_busy_s`` over everything it does between blocking reads."""
        now = time.perf_counter()
        busy = self._busy_s
        prev = self._util_anchors.get(anchor)
        self._util_anchors[anchor] = (now, busy)
        if prev is None or now - prev[0] <= 1e-6:
            return 0.0
        return min(max((busy - prev[1]) / (now - prev[0]), 0.0), 1.0)

    def _heartbeat_loop(self) -> None:
        """Dedicated heartbeat thread: keeps this replica's registry
        entry fresh even while the serve loop is wedged in a long model
        dispatch (the serve loop also beats opportunistically each
        iteration — ``_last_hb`` bounds the combined cadence). Exits
        with ``_stop``; a kill flips ``_killed`` first so the corpse
        stops refreshing even before the event is seen."""
        while not self._stop.wait(self.heartbeat_s):
            try:
                self._heartbeat()
            except Exception as e:
                log.debug("background heartbeat failed: %s", e)

    def _heartbeat(self, force: bool = False) -> None:
        """Publish this replica's state into the fleet registry (bounded
        cadence: ``heartbeat_s``) and refresh the pending/utilization
        gauges. Runs on the serve loop AND the dedicated heartbeat
        thread — ``_hb_lock`` serializes them: two concurrent beats
        would both pass the cadence check and the second would read the
        utilization anchor the first just wrote, publishing a spurious
        0.0 for a busy replica (a wrong-direction autoscaler sample).
        Failures log and drop."""
        if self._killed:
            return      # a corpse must not refresh its own heartbeat
        with self._hb_lock:
            now = time.monotonic()
            if not force and now - self._last_hb < self.heartbeat_s:
                return
            self._last_hb = now
            self._publish_heartbeat()

    def _publish_heartbeat(self) -> None:
        """One registry write + gauge refresh; caller holds ``_hb_lock``
        and has already passed the cadence check."""
        try:
            depth = self._stream_depth()
        except Exception:
            depth = 0
        pending = self._own_pending()
        if pending is not None:
            self._m_pending.set(pending)
        util = self._utilization("heartbeat")
        self._m_util.set(util)
        fleet_lib.publish_member(self.backend, self.stream,
                                 self.consumer_name, {
            "mode": self._mode,
            "depth": depth,
            "pending": pending,
            "watermark": self.shed_watermark,
            # the replica's own saturation verdict — what fleet
            # backpressure aggregates. Live work is backlog PLUS this
            # replica's own in-flight (delivered, unacked) entries: a
            # replica wedged in a long dispatch with a watermark-full
            # queue behind it is saturated even though its backlog
            # alone sits at the line
            "saturated": bool(self.shed_watermark > 0
                              and depth + (pending or 0)
                              > self.shed_watermark),
            "utilization": round(util, 4),
            "batch_target": (self._batch_ctl.value if self.adaptive_batch
                             else self.batch_size),
            # the scrape address (serve_metrics) — what the fleet
            # collector discovers targets from; None until mounted
            "endpoint": (f"{self._scrape.host}:{self._scrape.port}"
                         if self._scrape is not None else None),
        })

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ClusterServing":
        if self._thread is not None:
            raise RuntimeError("serving already started")
        self._killed = False
        self._busy_s = 0.0
        self._util_anchors = {}
        self._last_hb = 0.0
        # mixed-version fleet guard: refuse to double-serve a stream a
        # live peer consumes in an incompatible mode — BEFORE the first
        # read can steal an entry out from under the other mode's
        # accounting. Register FIRST, then check: check-then-register
        # would let two incompatible replicas starting concurrently each
        # pass the guard before either is visible; with our heartbeat
        # already published, at least one of them sees the other and
        # refuses (both refusing loudly beats both double-serving
        # silently). The loser deregisters so it does not haunt the
        # registry for a TTL. Raises loudly; the operator finishes the
        # rollout one mode at a time (docs/guides/SERVING.md runbook).
        self._heartbeat(force=True)     # registration: mode + first state
        try:
            fleet_lib.check_mode_conflict(self.backend, self.stream,
                                          self.consumer_name, self._mode,
                                          ttl_s=self.fleet_ttl_s)
            if self._group_mode:
                try:
                    self.backend.xgroup_create(self.stream,
                                               self.consumer_group)
                except (ConnectionError, OSError) as e:
                    raise RuntimeError(
                        f"cannot create consumer group "
                        f"{self.consumer_group!r} on stream "
                        f"{self.stream!r}: {e}") from e
        except Exception:
            fleet_lib.remove_member(self.backend, self.stream,
                                    self.consumer_name)
            raise
        self._stop.clear()
        self._t_last_flush = None   # a restart must not span the downtime
        self._crash_info = {}
        self._loop_down = set()
        if self.decode_workers > 0:
            self._pool = ThreadPoolExecutor(
                max_workers=self.decode_workers,
                thread_name_prefix="serving-decode")
        self._pub_queue = queue.Queue(maxsize=self._pub_maxsize)
        self._pub_thread = threading.Thread(
            target=self._supervised, args=("publish", self._publisher_loop),
            daemon=True, name="cluster-serving-publish")
        self._pub_thread.start()
        self._thread = threading.Thread(
            target=self._supervised, args=("serve", self._loop),
            daemon=True, name="cluster-serving")
        self._thread.start()
        # liveness must not ride serve-loop progress: a cold-start
        # compile or a multi-second model dispatch blocks the loop past
        # the fleet TTL, and a stale heartbeat makes a BUSY replica look
        # dead — peers would reclaim its in-flight entries early and a
        # mixed-mode starter would see no live peer to conflict with
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name="cluster-serving-heartbeat")
        self._hb_thread.start()
        return self

    def _supervised(self, name: str, body) -> None:
        """Run a loop body under restart supervision (the Ray
        actor-restart discipline): an escaped exception logs, records its
        traceback for /statusz, increments
        ``zoo_serving_loop_restarts_total{loop=name}`` and re-enters the
        body after a bounded backoff. After ``max_loop_restarts`` crashes
        the supervisor gives up — the loop lands in ``_loop_down`` and
        /healthz reads ``down`` (a crash-looping server must page, not
        flap forever). Clean returns (stop requested, publisher
        sentinel) end supervision."""
        delays = self._restart_policy.delays()
        crashes = 0
        while True:
            try:
                body()
                return
            except Exception:
                tb = traceback.format_exc()
                # each supervised loop writes its OWN key ("serve" /
                # "publish"): disjoint dict slots, one GIL-atomic
                # store each, and the only reader (/statusz) is
                # display-only — no read-modify-write to interleave
                self._crash_info[name] = tb  # zoolint: disable=ZL014 disjoint per-thread keys
                if self._stop.is_set():
                    return              # crashed into shutdown: just exit
                crashes += 1
                self.metrics.emit("serving.loop_crash", loop=name,
                                  crashes=crashes, traceback=tb)
                if crashes > self.max_loop_restarts:
                    log.error("%s loop crashed %d times; supervisor giving "
                              "up — /healthz now reads down:\n%s",
                              name, crashes, tb)
                    self._loop_down.add(name)
                    return
                delay = next(delays, self._restart_policy.max_delay)
                log.exception("%s loop crashed (%d/%d); restarting in "
                              "%.3fs", name, crashes,
                              self.max_loop_restarts, delay)
                self._m_restarts[name].inc()
                if self._stop.wait(delay):
                    return

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the loop; with ``drain`` first wait for the stream to
        empty. The publisher always drains: every batch the serve loop
        handed it is published before the sinks close. A backend that is
        already down cannot veto shutdown: the drain poll logs and skips
        instead of raising, and workers/sinks still join and close."""
        if self._thread is None:
            self._shutdown_workers(timeout)
            self._close_sinks()
            return
        if drain:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                try:
                    if self.backend.stream_len(self.stream) <= 0:
                        break
                except Exception as e:
                    log.warning("stop(drain=True): backend unavailable "
                                "(%s: %s); skipping the drain",
                                type(e).__name__, e)
                    break
                time.sleep(0.01)
        self._stop.set()
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            # keep the handle: a discarded live thread would let a second
            # start() race two consumers on the same stream
            raise TimeoutError(
                f"serve loop still running after {timeout}s (model dispatch "
                f"in flight?); call stop() again to re-join")
        self._thread = None
        self._shutdown_workers(timeout)
        self._close_sinks()
        # clean deregistration — a crash skips this and the fleet TTL
        # reaps the stale heartbeat instead
        fleet_lib.remove_member(self.backend, self.stream,
                                self.consumer_name)

    def kill(self, join: bool = True) -> None:
        """Die like a SIGKILL — the chaos/testing surface behind the
        fleet reclaim proof (``tests/test_fleet_chaos.py``).

        Stops both loops WITHOUT settling anything: no drain, no result
        publishes, no error answers, no acks, no fleet deregistration
        (the heartbeat just goes stale past the TTL). In consumer-group
        mode every entry this replica read but had not acked stays in
        the group's pending-entries set under this consumer's name until
        a surviving replica's reclaim sweep takes it over — exactly the
        crash window the group semantics exist to close. In-flight
        device work is abandoned (its replica permit with it). With
        ``join`` the threads ARE joined and sinks closed so the
        *process* stays clean — the simulated crash is at the
        serving-protocol level, not the OS level; ``join=False`` only
        flips the kill switch (a test whose model is still blocking the
        loop unblocks it afterwards, then calls ``kill()`` again to
        reap). Idempotent."""
        self._killed = True
        self._stop.set()
        if not join:
            return
        t = self._thread
        if t is not None:
            t.join(timeout=30.0)
            if t.is_alive():
                raise TimeoutError(
                    "killed serve loop still running after 30s (model "
                    "dispatch still blocked?); unblock it and call "
                    "kill() again")
            self._thread = None
        self._shutdown_workers()
        self._close_sinks()

    def _shutdown_workers(self, timeout: float = 30.0) -> None:
        """Join the publisher (after a drain-everything sentinel) and the
        decode pool. Safe to call when neither was started."""
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        hb = self._hb_thread
        if hb is not None:
            hb.join(timeout=timeout)    # exits on _stop; beats are short
            self._hb_thread = None
        t, q = self._pub_thread, self._pub_queue
        if t is None:
            return
        try:
            # bounded: with the queue full and the publisher wedged on a
            # stalled backend, a plain put() would block forever and the
            # TimeoutError below could never fire
            q.put(_PUB_STOP, timeout=timeout)
        except queue.Full:
            raise TimeoutError(
                f"publisher still draining after {timeout}s (result "
                f"backend stalled?); call stop() again to re-join")
        t.join(timeout=timeout)
        if t.is_alive():
            raise TimeoutError(
                f"publisher still draining after {timeout}s (result "
                f"backend stalled?); call stop() again to re-join")
        self._pub_thread = None
        self._pub_queue = None

    def _close_sinks(self) -> None:
        if self._summary is not None:
            self._summary.close()
            self._summary = None
        if self._scrape is not None:
            self._scrape.close()
            self._scrape = None
        if self._profiler is not None:
            self._profiler.close()   # stop an in-flight capture cleanly
            self._profiler = None
        if self._events is not None:
            self.metrics.remove_event_sink(self._events)
            self._events.close()
            self._events = None
        if self._dlq is not None:
            # seal (don't discard): a stopped server's active segment
            # becomes replayable — the handle is reopened on restart
            self._dlq.close()

    # -- the loop -----------------------------------------------------------
    def _gp_note(self, category: str) -> None:
        """Attribute wall clock since the ledger's mark to ``category``
        (no-op when goodput accounting is disabled)."""
        if self._goodput is not None:
            self._goodput.note(category)

    def _loop(self) -> None:
        """The continuous dispatch pipeline: per lane, up to
        ``max_inflight`` batches run their device time + dispatch
        round-trip while the next batch is read, routed, and assembled
        on the host (``predict_async`` enqueues the XLA work and defers
        only the readback). Admission is decoupled from the device step:
        while ANY lane has work in flight (or records buffered behind a
        half-open breaker probe / a restarted loop) the stream read is
        a non-blocking poll (``_BUSY_POLL_MS``), so records arriving
        during a device step join the NEXT dispatch instead of waiting
        out a read window — the device idles only when the stream is
        truly empty."""
        lanes = self._lanes
        if self._goodput is not None:
            self._goodput.open()
        try:
            while not self._stop.is_set():
                it0 = time.perf_counter()
                idle_s = 0.0
                try:
                    faults.inject("serving.loop")
                    busy = any(l.pendings or l.buffer
                               for l in lanes.values())
                    # admission window: `want` records are admitted
                    # (oldest first — FIFO fairness; weighted-fair
                    # across lanes under shed pressure); when the
                    # backlog stands above the shed watermark the read
                    # pulls the window's newest remainder too, purely to
                    # shed it — bounding the queue admitted records wait
                    # behind (their latency), while the shed ones get an
                    # immediate addressable error instead of a doomed
                    # wait
                    want = sum(self._lane_target(l) for l in lanes.values())
                    # reclaim sweep first: a dead peer's entries are the
                    # OLDEST work in the system — they take this read's
                    # admission slots ahead of fresh stream entries
                    reclaimed = self._reclaim_sweep()
                    buffered = sum(len(l.buffer) for l in lanes.values())
                    want_read = max(want - len(reclaimed) - buffered, 0)
                    extra = 0
                    if want_read > 0 and self.shed_watermark > 0 \
                            and self._breaker.state == CircuitBreaker.CLOSED:
                        # the pre-read depth probe respects the read
                        # breaker: while it is open/half-open the backend
                        # gets its probe read only — an extra depth probe
                        # per poll would burn a connection timeout
                        # against a dead host, exactly what the breaker
                        # exists to stop
                        overage = (self._stream_depth() - want_read
                                   - self.shed_watermark)
                        if overage > 0:
                            extra = min(overage, _SHED_MAX_PER_READ)
                    if want_read + extra > 0:
                        t_read = time.perf_counter()
                        entries = self._read_entries(
                            want_read + extra,
                            block_ms=_BUSY_POLL_MS if busy else None)
                        idle_s = time.perf_counter() - t_read
                    else:
                        entries = []
                    # read wait (and the pre-read sweep) is idle time —
                    # the device had nothing admitted to chew on
                    self._gp_note("idle")
                    if not entries and not reclaimed and not buffered:
                        self._drain_all()
                        self._gp_note("publish")
                        continue
                    if len(entries) > want_read:
                        admitted, shed = self._admit_fair(entries,
                                                          want_read)
                        self._shed(shed, reason="depth")
                        self._gp_note("shed")
                        entries = admitted
                    entries = reclaimed + entries
                    # ONE depth probe per read feeds both the gauge and
                    # the drain checks below — group consumers only ADD
                    # to each other's backlog view, so a stale 0 errs
                    # toward flushing, never toward parking
                    depth = self._stream_depth()
                    self._m_depth.set(depth)
                    routed = self._route(entries,
                                         n_reclaimed=len(reclaimed))
                    self._gp_note("host_decode")
                    for name, items in routed.items():
                        lane = lanes[name]
                        lane.buffer.extend(items)
                        lane.last_read_waits = [
                            i.wait for i in items if i.wait is not None]
                        if self.adaptive_batch and items:
                            self._update_batch_target(lane)
                    for lane in lanes.values():
                        self._pump(lane, depth)
                    self._gp_note("device_dispatch")
                finally:
                    # utilization accounting: everything this iteration
                    # did except the blocking read wait counts as busy;
                    # the heartbeat publishes it (bounded cadence) into
                    # the fleet registry and the gauges
                    self._busy_s += max(
                        time.perf_counter() - it0 - idle_s, 0.0)
                    self._heartbeat()
                    # residual per-iteration overhead (heartbeat,
                    # breaker bookkeeping, error unwind) lands on idle
                    # so no interval is ever left unattributed
                    self._gp_note("idle")
        finally:
            # exit — clean stop, crash (the supervisor may restart us),
            # or kill: dispatch what was already admitted (the records
            # were read; in legacy mode dropping them would lose them),
            # then flush every in-flight batch. A kill abandons instead
            # (the crash window the group reclaim exists to close), and
            # a failing final pump must not mask the original exception.
            for lane in lanes.values():
                if not self._killed:
                    try:
                        self._pump(lane, 0)
                    except Exception:
                        log.exception("final pump of lane %r failed",
                                      lane.name)
                self._drain(lane.pendings)

    def _drain(self, pendings) -> None:
        """Flush every pending batch, oldest first."""
        while pendings:
            self._flush(pendings.popleft())

    def _drain_all(self) -> None:
        """Flush every lane's dispatch window (the stream-empty drain
        signal: no next batch will arrive to overlap with, so deferring
        readbacks would only add tail latency under trickle load)."""
        for lane in self._lanes.values():
            self._drain(lane.pendings)

    def _admit_fair(self, entries, want_read: int):
        """Split an over-watermark read into ``(admitted, shed)``.

        Single lane: pure FIFO — the window's oldest ``want_read``
        records are admitted, the newest remainder shed (the original
        admission-control contract). Multiple lanes: **weighted-fair** —
        each lane keeps a quota of the admission window proportional to
        its configured weight (largest-remainder rounding, so quotas sum
        to exactly ``want_read``), filled oldest-first from its OWN
        records; quota a lane leaves unused (less traffic than its
        share) redistributes to the remaining records in global FIFO
        order. Records addressed to no configured lane ride for free —
        they cost one error write, not a dispatch slot."""
        if len(self._lanes) == 1:
            return entries[:want_read], entries[want_read:]
        names = [self._lane_name(fields) for _eid, fields in entries]
        by_lane: Dict[str, List[int]] = {}
        for idx, name in enumerate(names):
            if name is not None:
                by_lane.setdefault(name, []).append(idx)
        total_w = sum(l.weight for l in self._lanes.values())
        shares = {n: want_read * l.weight / total_w
                  for n, l in self._lanes.items()}
        quota = {n: int(s) for n, s in shares.items()}
        rem = want_read - sum(quota.values())
        for n in sorted(shares, key=lambda n: (-(shares[n] - quota[n]), n)):
            if rem <= 0:
                break
            quota[n] += 1
            rem -= 1
        admitted = {idx for idx, name in enumerate(names) if name is None}
        taken = 0
        for n, idxs in by_lane.items():
            keep = idxs[:quota.get(n, 0)]
            admitted.update(keep)
            taken += len(keep)
        leftover = want_read - taken
        if leftover > 0:
            for idx, name in enumerate(names):
                if leftover <= 0:
                    break
                if name is not None and idx not in admitted:
                    admitted.add(idx)
                    leftover -= 1
        keep_list = [e for i, e in enumerate(entries) if i in admitted]
        shed_list = [e for i, e in enumerate(entries) if i not in admitted]
        return keep_list, shed_list

    def _read_entries(self, count: Optional[int] = None,
                      block_ms: Optional[int] = None):
        """One breaker-guarded stream read of up to ``count`` entries
        (default ``batch_size``; admission control reads more when there
        is overage to shed, adaptive batching less). ``block_ms``
        overrides the configured read block — the continuous-batching
        busy poll passes ``_BUSY_POLL_MS`` so in-flight work is never
        parked behind a full read window. Transport failures
        (``ConnectionError``/``OSError`` — a dropped Redis connection)
        are absorbed HERE: they count against the breaker and return an
        empty read instead of killing the loop, so a blip costs one poll
        interval, not a loop restart. While the breaker is open the
        backend is left alone until the next probe window (the wait is
        stop-aware). Anything non-transport still escapes to the
        supervisor — a bug must restart the loop loudly, not spin
        silently."""
        if count is None:
            count = self.batch_size
        if block_ms is None:
            block_ms = self.block_ms
        if not self._breaker.allow():
            self._stop.wait(min(max(self._breaker.probe_in(), 0.001),
                                self.block_ms / 1000.0))
            return []
        try:
            if self._group_mode:
                # group read: the entry lands in the PEL under OUR name
                # instead of being consumed — the ack (post-settlement)
                # is what finally removes it. A transport error here MAY
                # have delivered entries whose reply was lost; they sit
                # in our own PEL and the reclaim sweep re-claims them
                # once idle (XREADGROUP is never blind-retried).
                entries = self.backend.xreadgroup(
                    self.stream, self.consumer_group, self.consumer_name,
                    count, block_ms=block_ms)
            else:
                entries = self.backend.xread(self.stream, count,
                                             block_ms=block_ms)
        except (ConnectionError, OSError) as e:
            self._breaker.record_failure()
            log.warning("input-stream read failed (%s: %s); breaker %s",
                        type(e).__name__, e, self._breaker.state)
            self.metrics.emit("serving.backend_error", op="xread",
                              error=f"{type(e).__name__}: {e}",
                              breaker=self._breaker.state)
            return []
        except Exception:
            # non-transport escape (a bug, a protocol error): resolve the
            # admitted call as a failure BEFORE the supervisor takes over
            # — a half-open probe slot left in flight would refuse every
            # future allow() and wedge the restarted loop forever
            self._breaker.record_failure()
            raise
        self._breaker.record_success()
        return entries

    def _stream_depth(self) -> int:
        """Post-read depth for the gauge/drain checks; a failing backend
        reads as 0, which errs toward flushing (never toward parking a
        dispatched batch behind a dead backend). A 0 also disables the
        shed overage for that iteration — admission control must never
        shed on a backend blip's missing reading. In group mode this is
        the UNDELIVERED backlog (``backlog_len``): on real Redis XLEN
        still counts delivered-but-unacked entries, and counting our own
        in-flight batch as queue depth would defeat the trickle-load
        drain signal and inflate the shed overage."""
        try:
            if self._group_mode and hasattr(self.backend, "backlog_len"):
                return self.backend.backlog_len(self.stream,
                                                self.consumer_group)
            return self.backend.stream_len(self.stream)
        except (ConnectionError, OSError) as e:
            log.debug("stream_len failed after a read: %s", e)
            return 0

    def _reclaim_sweep(self) -> List[Tuple[str, dict]]:
        """Take over pending entries whose owner has gone quiet
        (``claim_idle_ms``) — a dead peer's in-flight reads, or our own
        reads whose XREADGROUP reply was lost. Bounded cadence
        (``claim_sweep_s``) and batch (``batch_size``). Reclaimed
        entries re-enter the NORMAL pipeline — decode, dispatch,
        publish, ack — so a record the dead peer had in fact already
        answered simply re-answers idempotently (same uri, same
        prediction). Entries past ``max_deliveries`` are poison hopping
        replica to replica: answered with an addressable error and
        settled instead of reclaiming forever. Transport failures log
        and skip (the sweep retries next interval); a genuine bug still
        escapes to the supervisor."""
        if not self._group_mode:
            return []
        now = time.monotonic()
        if now - self._last_sweep < self.claim_sweep_s:
            return []
        self._last_sweep = now
        try:
            claimed = self.backend.xautoclaim(
                self.stream, self.consumer_group, self.consumer_name,
                self.claim_idle_ms, count=self.batch_size)
        except (ConnectionError, OSError) as e:
            log.warning("reclaim sweep failed (%s: %s); retrying next "
                        "interval", type(e).__name__, e)
            return []
        out: List[Tuple[str, dict]] = []
        for eid, fields, prev, deliveries in claimed:
            # from = the dead peer's consumer name: bounded by fleet
            # membership (and reaped identities), not request data
            self.metrics.counter(  # zoolint: disable=ZL015 bounded label set
                "zoo_serving_reclaimed_total",
                "pending entries taken over from an idle consumer, by "
                "previous owner",
                labels={"from": prev}).inc()
            self.metrics.emit("serving.reclaim", entry=eid,
                              uri=fields.get("uri"),
                              trace=fields.get("trace"),
                              prev_consumer=prev, deliveries=deliveries)
            if deliveries > self.max_deliveries:
                log.error("entry %s (uri=%r) delivered %d times (max "
                          "%d); dead-lettering instead of reclaiming "
                          "forever", eid, fields.get("uri"), deliveries,
                          self.max_deliveries)
                self._m_dead_letter.inc()
                self.metrics.emit("serving.dead_letter",
                                  uri=fields.get("uri"),
                                  trace=fields.get("trace"),
                                  error="exceeded max deliveries")
                self._settle_drop(
                    fields, eid,
                    error="dead-lettered: exceeded max deliveries")
                continue
            out.append((eid, fields))
        return out

    def _settle_drop(self, fields: dict, eid: Optional[str],
                     error: str) -> None:
        """Answer a record with an addressable error and ack it — the
        settlement for records serving gives up on at READ time (no
        dispatch, no trace phases in flight). The ack happens only when
        the producer-visible answer landed (or there is no uri to
        answer): an unanswered drop must stay pending so a later
        reclaim can re-answer it."""
        self._m_failures.inc()
        # error = one of the addressable failure strings the server
        # itself writes (see the catalog row) — a closed set
        self.metrics.counter(  # zoolint: disable=ZL015 bounded label set
            "zoo_serving_failure_errors_total",
            "failed records by error kind (model vs result-store)",
            labels={"error": error}).inc()
        uri = fields.get("uri")
        if not uri:
            self._ack([eid])
            return
        try:
            self.backend.set_result(uri, {"error": error})
        except Exception:
            log.exception("error record for %r could not be written "
                          "(backend down?); entry stays pending", uri)
            return
        self._ack([eid])

    def _ack(self, eids) -> None:
        """Settle entries out of the group's PEL — called ONLY after the
        producer-visible outcome landed (result publish, addressable
        error answer, shed answer, or a durable DLQ spill). An ack that
        fails leaves the entries pending: a survivor (or this replica's
        own next sweep) re-claims and re-answers them idempotently —
        the at-least-once half of the exactly-once-settlement story.
        Counts only entries actually removed, so a double ack (reclaim
        raced a slow publish) never double-counts."""
        if not self._group_mode:
            return
        eids = [e for e in eids if e]
        if not eids:
            return
        try:
            n = self.backend.xack(self.stream, self.consumer_group, *eids)
        except Exception as e:
            log.warning("ack of %d entries failed (%s: %s); they stay "
                        "pending and will be re-served by a reclaim",
                        len(eids), type(e).__name__, e)
            self.metrics.emit("serving.ack_failed", entries=len(eids),
                              error=f"{type(e).__name__}: {e}")
            return
        if n:
            self._m_acks.inc(n)

    # -- overload: shedding + adaptive batch ---------------------------------
    def _shed(self, entries, reason: str) -> None:
        """Answer shed records with the distinct addressable ``shed:
        server overloaded`` error — no decode, no dispatch, one batched
        error write for the whole set. Runs BEFORE any trace event is
        emitted, so a shed record leaves no dangling trace (the shed
        counters + its error answer are its whole story). Sheds are
        degradation, not loop failure: a result store refusing the
        error writes logs and moves on."""
        # counters resolved ONCE per shed set (a flood sheds up to
        # _SHED_MAX_PER_READ records per iteration — per-record label
        # lookups are exactly the cost this path must not pay); the
        # per-record emit stays: the shed event is the ONLY trace these
        # records leave, and emit() is a no-op without sinks
        n = len(entries)
        self._m_shed[reason].inc(n)
        self._m_failures.inc(n)
        self.metrics.counter(
            "zoo_serving_failure_errors_total",
            "failed records by error kind (model vs result-store)",
            labels={"error": "shed: server overloaded"}).inc(n)
        results = {}
        addressable_eids = []
        orphan_eids = []
        for eid, fields in entries:
            uri = fields.get("uri")
            self.metrics.emit("serving.shed", reason=reason, uri=uri,
                              trace=fields.get("trace"))
            if uri:
                results[uri] = {"error": "shed: server overloaded"}
                addressable_eids.append(eid)
            else:
                orphan_eids.append(eid)
        # no address, no answer to wait for: settled by the drop itself
        self._ack(orphan_eids)
        if not results:
            return
        try:
            set_results = getattr(self.backend, "set_results", None)
            if set_results is not None:
                set_results(results)
            else:
                for uri, fields in results.items():
                    self.backend.set_result(uri, fields)
        except Exception:
            log.exception("shed-error records for %d record(s) could not "
                          "be written (backend down?); entries stay "
                          "pending", len(results))
            return
        self._ack(addressable_eids)

    def _update_batch_target(self, lane: _Lane) -> None:
        """One AIMD step per lane per non-empty read. Breach = the
        publish backlog above half its bound (the publisher is falling
        behind) OR this READ's queue-wait p95 for THIS lane's records
        above target (records are aging in the stream). The current
        read's waits — not the cumulative digest — drive the
        controller: control needs a live signal that recovers when the
        overload clears, and it keeps the trajectory a pure function of
        the traffic (deterministic under test)."""
        backlog = 0 if self._pub_queue is None else self._pub_queue.qsize()
        breach = backlog > self._pub_maxsize // 2
        waits = lane.last_read_waits
        if not breach and waits:
            w = sorted(waits)
            breach = w[-(-len(w) * 95 // 100) - 1] > self.queue_wait_target_s
        target = lane.batch_ctl.update(breach)
        lane.m_target.set(target)
        if lane.name == self._primary:
            self._m_batch_target.set(target)

    # -- routing + batch assembly --------------------------------------------
    def _route(self, entries, n_reclaimed: int = 0):
        """Validate one read and route each record to its lane:
        ``{lane_name: [_Item, ...]}``, read order preserved. The first
        ``n_reclaimed`` entries came from the reclaim sweep (the loop
        prepends them) — they serve normally but are excluded from the
        queue-wait signal (see ``_observe_queue_wait``).

        Per record: queue wait observed, then the cheap drops — missing
        uri, unknown ``model``, expired/doomed deadline, undecodable
        payload — all answered BEFORE any trace event is emitted, so a
        dropped record leaves no dangling trace. v2 headers are
        validated inline (the shared accept rule, ``client.validate_v2``
        — after it passes the later arena copy is a pure memcpy that
        cannot fail); legacy v1 payloads are decoded here on the worker
        pool (the base64+``.npy`` work releases the GIL). The two
        enqueue/dequeue phase events are emitted at admission."""
        now_s = time.time()
        now_p = time.perf_counter()
        staged: List[Tuple[str, _Item]] = []
        v1_idx: List[int] = []
        for idx, (eid, fields) in enumerate(entries):
            wait, t_enq = self._observe_queue_wait(
                eid, now_s, reclaimed=idx < n_reclaimed)
            uri = fields.get("uri")
            if not uri:
                # a decodable payload with a missing uri must be dropped
                # whole — an orphan tensor would misalign every later
                # uri with the wrong prediction, and there is no address
                # to write an error record to
                log.error("record with no uri dropped (entry id %s)", eid)
                self._drop_undecodable(fields, eid)
                continue
            lane_name = self._lane_name(fields)
            if lane_name is None:
                self._drop_unknown_model(fields, eid)
                continue
            verdict = self._deadline_verdict(fields, now_s)
            if verdict is not None:
                # answered BEFORE validation/decode/dispatch spend
                # anything on a record whose producer has already given
                # up (expired) or will have by the time a dispatch could
                # answer it (doomed — deadline-aware admission control)
                self._drop_expired(fields, doomed=(verdict == "doomed"),
                                   eid=eid)
                continue
            hdr = None
            if is_v2(fields):
                try:
                    hdr = validate_v2(fields)
                except Exception:
                    log.exception("undecodable record (uri=%r)", uri)
                    self._drop_undecodable(fields, eid)
                    continue
            else:
                v1_idx.append(len(staged))
            staged.append((lane_name, _Item(
                _Rec(uri, fields.get("trace"), t_enq, now_p,
                     hdr is not None,
                     eid if self._group_mode else None),
                fields, wait, hdr, None)))
        if v1_idx:
            def decode_one(i):
                name, item = staged[i]
                try:
                    arr = np.asarray(decode_payload(item.fields))
                except Exception:
                    log.exception("undecodable record (uri=%r)",
                                  item.rec.uri)
                    self._drop_undecodable(item.fields, item.rec.eid)
                    return None
                return (name, item._replace(arr=arr))

            if self._pool is not None and len(v1_idx) > 1:
                decoded = list(self._pool.map(decode_one, v1_idx))
            else:
                decoded = [decode_one(i) for i in v1_idx]
            for i, repl in zip(v1_idx, decoded):
                staged[i] = repl
        routed: "collections.OrderedDict[str, List[_Item]]" = \
            collections.OrderedDict((n, []) for n in self._lanes)
        for pair in staged:
            if pair is None:
                continue        # a v1 record that failed its decode
            name, item = pair
            routed[name].append(item)
        self._emit_read_events(
            [i for items in routed.values() for i in items])
        self._m_decode.observe(time.perf_counter() - now_p)
        return routed

    def _take_run(self, lane: _Lane) -> List[_Item]:
        """Pop the lane's front run of same-(shape, dtype) records, up
        to its batch target — one dispatchable batch. Mixed-shape
        traffic splits into consecutive uniform runs (each run gets its
        own bucket-padded arena), so an odd-shaped record costs its own
        dispatch, never a misassembled batch."""
        target = max(self._lane_target(lane), 1)
        items: List[_Item] = []
        key0 = None
        while lane.buffer and len(items) < target:
            item = lane.buffer[0]
            if item.hdr is not None:
                key = (item.hdr[2], item.hdr[1].str)
            else:
                key = (item.arr.shape, item.arr.dtype.str)
            if key0 is None:
                key0 = key
            elif key != key0:
                break
            items.append(lane.buffer.popleft())
        return items

    @staticmethod
    def _item_array(item: _Item) -> np.ndarray:
        """One admitted record's tensor (zero-copy view for v2)."""
        if item.arr is not None:
            return item.arr
        payload, dt, shape = item.hdr
        return np.frombuffer(payload, dtype=dt).reshape(shape)

    def _lane_assemble(self, lane: _Lane, items: List[_Item]):
        """Assemble one uniform run into ``(recs, batch, arena)``.

        Normal path: a pooled arena row per record plus **bucket
        padding** — the batch is padded up to the lane's smallest
        compiled-shape bucket ≥ ``len(items)`` by repeating the last
        real row, so ragged traffic reuses a handful of compiled
        programs instead of retracing per distinct size. Padding rows
        are accounted (``zoo_serving_bucket_pad_rows_total``) and
        sliced off at readback — they never publish. Oversized rows
        (``batch_size`` of them would exceed ``_MAX_ARENA_BYTES``)
        assemble via ``np.stack`` with no arena and no padding: the
        allocation stays proportional to the bytes actually received."""
        t0 = time.perf_counter()
        first = items[0]
        if first.hdr is not None:
            _, dt, shape = first.hdr
            rowbytes = len(first.hdr[0])
        else:
            dt, shape = first.arr.dtype, first.arr.shape
            rowbytes = first.arr.nbytes
        k = len(items)
        recs = [i.rec for i in items]
        if rowbytes * lane.batch_size > _MAX_ARENA_BYTES:
            batch = np.stack([self._item_array(i) for i in items])
            lane.dispatches += 1
            lane.bucket_hits += 1   # no padding on the fallback path
            self._m_decode.observe(time.perf_counter() - t0)
            return recs, batch, None
        bucket = lane.bucket_for(k)
        arena = lane.arena_pool.acquire(shape, dt)
        self._copy_rows(arena, items)
        if bucket > k:
            arena[k:bucket] = arena[k - 1]
            lane.m_pad_rows.inc(bucket - k)
        else:
            lane.bucket_hits += 1
        lane.dispatches += 1
        self._m_decode.observe(time.perf_counter() - t0)
        return recs, arena[:bucket], arena

    def _copy_rows(self, arena: np.ndarray, items: List[_Item]) -> None:
        """Copy each record's tensor into its arena row, split across
        the decode workers in contiguous slices. Consecutive v2 payloads
        in a slice are joined and copied with ONE ``np.copyto`` onto a
        flat arena view — a single GIL-releasing memcpy, no Python-level
        per-row loop (the in-process fleet scaling fix: per-row
        assignments serialized replicas on the GIL); already-decoded v1
        rows copy individually (rare path)."""
        k = len(items)
        # explicit row element count, never reshape(-1): a zero-size row
        # (shape "0" validates) makes -1 ambiguous and the raise would
        # escape a decode worker into the serve loop
        row_elems = int(np.prod(arena.shape[1:], dtype=np.int64))
        flat = arena.reshape(arena.shape[0], row_elems)

        def copy_slice(lo: int, hi: int) -> None:
            i = lo
            while i < hi:
                item = items[i]
                if item.hdr is None:
                    np.copyto(arena[i], item.arr)
                    i += 1
                    continue
                j = i + 1
                while j < hi and items[j].hdr is not None:
                    j += 1
                buf = (items[i].hdr[0] if j == i + 1
                       else b"".join(items[m].hdr[0] for m in range(i, j)))
                src = np.frombuffer(buf, dtype=arena.dtype)
                np.copyto(flat[i:j], src.reshape(j - i, row_elems))
                i = j

        if self._pool is not None and self.decode_workers > 1 \
                and k >= 2 * self.decode_workers:
            step = -(-k // self.decode_workers)
            futs = [self._pool.submit(copy_slice, lo, min(lo + step, k))
                    for lo in range(0, k, step)]
            for f in futs:
                f.result()
        else:
            copy_slice(0, k)

    def _pump(self, lane: _Lane, depth: int) -> None:
        """Dispatch a lane's admitted records in bucket-shaped batches —
        the continuous half of the pipeline: everything buffered (this
        read's records plus any carried over a half-open probe or loop
        restart) rides the next device step NOW. The lane's dispatch
        breaker gates the model: while OPEN, buffered records fast-fail
        to addressable errors (+ durable DLQ spills) instead of burning
        the shared loop — the other lanes keep dispatching; while the
        HALF-OPEN probe is in flight, records wait buffered for its
        verdict. Tail rule: with the stream empty and nothing left to
        overlap, the window drains (the trickle-load latency
        contract)."""
        if self._killed:
            return
        blocked = False
        while lane.buffer:
            if not lane.breaker.allow():
                if lane.breaker.state == CircuitBreaker.OPEN:
                    self._lane_fail_fast(lane)
                else:
                    # half-open with the probe batch still in flight:
                    # leave the records buffered — they ride the next
                    # step once the probe resolves at its readback
                    # (fail-fasting them would shed recoverable work on
                    # the mend)
                    blocked = True
                break
            items = self._take_run(lane)
            if not items:
                break
            recs, batch, arena = self._lane_assemble(lane, items)
            self._dispatch(lane, recs, batch, arena)
            while len(lane.pendings) >= lane.max_inflight:
                # the dispatch window (per lane — a capped big-model
                # lane drains earlier): publish the oldest batch once
                # max_inflight are dispatched-but-unread
                self._flush(lane.pendings.popleft())
        if lane.pendings and (blocked
                              or (depth == 0 and not lane.buffer)):
            # two reasons to flush now rather than defer: (a) the
            # stream is drained and there is no next batch to overlap
            # with, so deferring readbacks would only add up to
            # block_ms of tail latency under trickle load (ADVICE
            # round 5); (b) dispatch is blocked on the half-open
            # probe's verdict — nothing else resolves it, and under
            # sustained traffic the buffer would otherwise grow
            # unboundedly behind an unflushed probe
            self._drain(lane.pendings)

    def _lane_fail_fast(self, lane: _Lane) -> None:
        """The lane's dispatch breaker is open: answer everything it has
        admitted with the distinct addressable ``model unavailable``
        error — durably spilled to the DLQ first when one is attached
        (reason ``dispatch``; ``zoo-dlq replay`` re-enqueues them with
        their ``model`` field intact once the model recovers). This is
        the isolation half of multiplexing: a dead model degrades ITS
        lane while the loop keeps serving the others."""
        items = list(lane.buffer)
        lane.buffer.clear()
        if not items:
            return
        recs = [i.rec for i in items]
        self.metrics.emit("serving.lane_fail_fast", model=lane.name,
                          records=len(recs), breaker=lane.breaker.state)
        if self._dlq is not None:
            spilled = []
            for item in items:
                try:
                    self._dlq.append(item.rec.uri, self._item_array(item),
                                     reason="dispatch",
                                     trace=item.rec.trace,
                                     error="model unavailable",
                                     model=lane.name)
                except Exception:
                    log.exception("DLQ spill failed for fast-failed "
                                  "record %r", item.rec.uri)
                    continue
                spilled.append(item.rec.eid)
            self._ack(spilled)
        self._record_failure(recs, parent="dequeue",
                             error="model unavailable")

    def _deadline_verdict(self, fields, now_s: float) -> Optional[str]:
        """``"expired"`` when the record's producer-stamped
        ``deadline_ms`` (absolute epoch ms, the clock the entry ids
        already share) has passed; ``"doomed"`` when it has not, but the
        live dispatch-latency estimate (the quantile digest's median)
        says no dispatch could answer it in time — the deadline-aware
        half of admission control: spending a dispatch on a record whose
        caller is guaranteed to time out only delays the records behind
        it. Engages only after ``_DOOMED_MIN_OBS`` dispatched batches,
        so the one-time jit-compile outlier cannot inflate the estimate
        into refusing steady-state traffic. None serves. Malformed
        stamps serve anyway — a producer bug must not turn into dropped
        traffic."""
        dl = fields.get("deadline_ms")
        if dl is None:
            return None
        try:
            dl_ms = float(str(dl))
        except (TypeError, ValueError):
            log.warning("unparseable deadline_ms %r; serving the record "
                        "without a deadline", dl)
            return None
        if now_s * 1000.0 > dl_ms:
            return "expired"
        if self._q_dispatch.count >= _DOOMED_MIN_OBS \
                and (now_s + self._q_dispatch.quantile(0.5)) * 1000.0 > dl_ms:
            return "doomed"
        return None

    def _drop_expired(self, fields, doomed: bool = False,
                      eid: Optional[str] = None) -> None:
        """Answer an expired (or doomed — see ``_deadline_verdict``)
        record with the distinct ``deadline exceeded`` error — counted
        in its own family AND the error-labeled failure breakdown, so an
        operator can tell a deadline storm from a broken model in one
        scrape; a doomed record additionally counts as a shed
        (``zoo_serving_shed_total{reason="deadline"}``) — it was
        admission control, not a late producer. Like
        ``_drop_undecodable``, no phase events were emitted yet, so the
        drop leaves no dangling trace."""
        self._m_deadline.inc()
        if doomed:
            self._m_shed["deadline"].inc()
        self._m_failures.inc()
        self.metrics.counter(
            "zoo_serving_failure_errors_total",
            "failed records by error kind (model vs result-store)",
            labels={"error": "deadline exceeded"}).inc()
        self.metrics.emit("serving.deadline", uri=fields.get("uri"),
                          trace=fields.get("trace"),
                          deadline_ms=fields.get("deadline_ms"),
                          shed=doomed)
        try:
            self.backend.set_result(fields["uri"],
                                    {"error": "deadline exceeded"})
        except Exception:
            log.exception("deadline-error record for %r could not be "
                          "written (backend down?); entry stays pending",
                          fields.get("uri"))
            return
        self._ack([eid])

    def _drop_undecodable(self, fields, eid: Optional[str] = None) -> None:
        """Registry + event + (when addressable) an error record so the
        producer's ``query()`` fails fast instead of blocking out its
        full timeout. Runs on the serve loop: a result store refusing
        the write must not escalate a dropped record into loop death.
        Settlement: the error answer landing (or there being no uri to
        answer) acks the entry; a failed answer leaves it pending for a
        reclaim to re-answer."""
        self._m_undecodable.inc()
        self.metrics.emit("serving.undecodable", uri=fields.get("uri"),
                          trace=fields.get("trace"))
        if fields.get("uri"):
            try:
                self.backend.set_result(fields["uri"],
                                        {"error": "undecodable payload"})
            except Exception:
                log.exception("undecodable-error record for %r could not "
                              "be written (backend down?); entry stays "
                              "pending", fields["uri"])
                return
        self._ack([eid])

    def _drop_unknown_model(self, fields, eid: Optional[str] = None) -> None:
        """Answer a record routed to no configured lane with the distinct
        addressable ``unknown model`` error — before any trace event, so
        the drop leaves no dangling trace. The requested name goes to
        the log/event only (the failure-error label set stays closed).
        Settlement mirrors ``_drop_undecodable``: the ack lands only
        once the error answer did."""
        self._m_failures.inc()
        self.metrics.counter(
            "zoo_serving_failure_errors_total",
            "failed records by error kind (model vs result-store)",
            labels={"error": "unknown model"}).inc()
        log.error("record %r names unknown model %r (lanes: %s)",
                  fields.get("uri"), fields.get("model"),
                  ", ".join(self._lanes))
        self.metrics.emit("serving.unknown_model", uri=fields.get("uri"),
                          trace=fields.get("trace"),
                          model=fields.get("model"))
        if fields.get("uri"):
            try:
                self.backend.set_result(fields["uri"],
                                        {"error": "unknown model"})
            except Exception:
                log.exception("unknown-model error record for %r could "
                              "not be written (backend down?); entry "
                              "stays pending", fields["uri"])
                return
        self._ack([eid])

    def _emit_read_events(self, items) -> None:
        """The first two phase events per traced record; later phases
        (dispatch, publish) link back via the trace id + parent field."""
        for item in items:
            rec = item.rec
            if rec.trace is not None:
                self.metrics.emit("request", phase="enqueue",
                                  trace=rec.trace, uri=rec.uri,
                                  parent=None, at_s=rec.t_enq)
                self.metrics.emit("request", phase="dequeue",
                                  trace=rec.trace, uri=rec.uri,
                                  parent="enqueue", dur_s=item.wait)

    def _observe_queue_wait(self, entry_id, now_s: float,
                            reclaimed: bool = False):
        """Enqueue→read wait from the stream entry id (both backends stamp
        ids as ``<epoch_ms>-<seq>``, the Redis-stream convention).
        Returns ``(wait_s, enqueue_epoch_s)`` for the per-request trace
        events, ``(None, None)`` on a foreign id scheme. A negative wait
        (client clock ahead of the server) clamps to zero and counts in
        ``zoo_serving_clock_skew_total`` instead of polluting the
        distribution with a bogus near-zero-or-negative sample.
        ``reclaimed`` entries report ``(None, t_enq)``: their age is
        dominated by the dead peer's ``claim_idle_ms`` window, not this
        replica's admission health — observing it would land a 30 s+
        outlier in the queue-wait quantiles AND hand the adaptive-batch
        AIMD controller a guaranteed-over-target p95, collapsing the
        survivor's batch size exactly when it must absorb the dead
        peer's load (the ``serving.reclaim`` event carries the entry id,
        so the true age stays traceable)."""
        try:
            enq_ms = int(str(entry_id).split("-", 1)[0])
        except (TypeError, ValueError):
            return None, None   # foreign id scheme: skip, never break loop
        t_enq = enq_ms / 1000.0
        if reclaimed:
            return None, t_enq
        wait = now_s - t_enq
        if wait < 0:
            self._m_skew.inc()
            wait = 0.0
        self._m_queue_wait.observe(wait)
        self._q_queue_wait.observe(wait)
        return wait, t_enq

    # -- dispatch ------------------------------------------------------------
    def _dispatch(self, lane: _Lane, recs, batch, arena=None) -> None:
        """Enqueue the device work on the lane's model; appends a
        ``_Pending`` to the lane's window (async models) or publishes
        immediately (sync models). ``batch`` may be bucket-padded past
        ``len(recs)`` — the padding rows ride the dispatch and are
        sliced off at readback. Tries a NON-blocking async dispatch
        first: with a single replica permit (``concurrent_num=1``)
        dispatching before collecting our own pending batches would
        deadlock, so on a busy model pending batches are flushed
        oldest-first (releasing their permits) and the dispatch retried,
        blocking only once the window is empty. Models without
        predict_async (the server accepts any ``.predict``) compute
        synchronously — there is nothing to overlap, so the window is
        drained BEFORE the blocking predict and this batch publishes
        immediately (deferring either would only add latency). Outcomes
        feed the lane's dispatch breaker."""
        t0 = time.perf_counter()
        pendings = lane.pendings
        arena_owned = True
        # durable dead letters need the ORIGINAL request payloads at
        # publish time (the arena is recycled after readback): one
        # batch-sized copy per dispatch, paid only with a DLQ attached
        inputs = (np.array(batch[:len(recs)]) if self._dlq is not None
                  and batch is not None else None)
        try:
            faults.inject("serving.dispatch")
            async_fn = getattr(lane.model, "predict_async", None)
            if async_fn is not None:
                collect = self._probe_dispatch(async_fn, batch, len(recs))
                while collect is None and pendings:
                    # all replica permits in flight: publish the oldest
                    # pending batch (releasing its permit) and retry
                    self._flush(pendings.popleft())
                    collect = self._probe_dispatch(async_fn, batch,
                                                   len(recs))
                if collect is None:
                    # a replica permit may be held by ANOTHER lane's
                    # pending batch (lane specs may alias one model):
                    # release every window before a blocking dispatch
                    # on this single thread could deadlock the loop
                    self._drain_all()
                    collect = self._probe_dispatch(async_fn, batch,
                                                   len(recs))
                if collect is None:
                    with span("serving.dispatch", registry=self.metrics,
                              records=len(recs)):
                        collect = async_fn(batch)
                # breaker success is recorded at READBACK (_flush), not
                # here: an async model's real failures surface at
                # collect(), and a success stamped at enqueue time would
                # interleave with them and keep resetting the
                # consecutive-failure count — the breaker would never
                # open on a model that crashes every readback
                lane.m_dispatches.inc()
                self._emit_dispatch(recs, t0)
                arena_owned = False
                pendings.append(_Pending(lane, recs, collect, t0, arena,
                                         inputs))
                return
            self._drain(pendings)
            with span("serving.dispatch", registry=self.metrics,
                      records=len(recs)):
                preds = lane.model.predict(batch)
            # breaker success lands in _flush below (one signal source)
            lane.m_dispatches.inc()
            self._emit_dispatch(recs, t0)
            arena_owned = False
            self._flush(_Pending(lane, recs, (lambda: preds), t0, arena,
                                 inputs))
        except Exception as e:
            lane.breaker.record_failure()
            log.exception("inference dispatch failed for %d records "
                          "(model %r); retrying one record at a time",
                          len(recs), lane.name)
            # copy each record's input out BEFORE the arena goes back to
            # the pool — a later read may overwrite it mid-retry
            rows = None
            if batch is not None and self.dispatch_retries > 0:
                rows = [np.array(batch[i:i + 1]) for i in range(len(recs))]
            if arena_owned:
                lane.arena_pool.release(arena)
            self._retry_or_dead_letter(lane, recs, rows, cause=e)

    @staticmethod
    def _predict_once(model, batch):
        """One synchronous model call for the retry path (the server
        accepts models exposing either surface)."""
        predict = getattr(model, "predict", None)
        if predict is not None:
            return predict(batch)
        return model.predict_async(batch)()

    def _retry_or_dead_letter(self, lane: _Lane, recs, rows,
                              cause: Optional[BaseException] = None) -> None:
        """After a batch dispatch crash: re-dispatch each record ALONE,
        up to ``dispatch_retries`` times. One poison record (a payload
        that crashes the model) must not fail its batch-mates — they
        serve from their solo retries — and must itself be dead-lettered
        with an addressable error instead of being retried forever.
        ``cause`` is the batch-crash exception, preserved in the
        dead-letter event when a drained retry budget refuses the solo
        attempts (the operator debugging the outage needs the REAL
        error, not 'budget exhausted'). Runs synchronously on the serve
        loop: the crashed batch already forfeited its pipeline slot, and
        bounded-blocking here is the backpressure."""
        if self._killed:
            return
        if rows is None:
            self._record_failure(recs, parent="dequeue")
            return
        # release EVERY window's replica permits first: a blocking solo
        # predict with a permit tied up in any lane's pendings (lane
        # specs may alias one model) would deadlock exactly like the
        # dispatch-before-flush order this loop avoids
        self._drain_all()
        retry_counter = self.metrics.counter(
            "zoo_retry_attempts_total",
            "retries performed by reliability.RetryPolicy, by operation",
            labels={"op": "serving.dispatch"})
        for rec, row in zip(recs, rows):
            err = None
            budget_refused = False
            for attempt in range(self.dispatch_retries):
                if (self._retry_budget is not None
                        and not self._retry_budget.withdraw()):
                    # the shared budget is drained (correlated outage):
                    # skip the solo retry and dead-letter addressably,
                    # keeping the ORIGINAL batch-crash error as the cause
                    budget_refused = True
                    err = cause if cause is not None else RuntimeError(
                        "retry budget exhausted")
                    break
                retry_counter.inc()     # every solo re-dispatch is a retry
                t1 = time.perf_counter()
                try:
                    faults.inject("serving.dispatch")
                    with span("serving.dispatch", registry=self.metrics,
                              records=1):
                        preds = np.asarray(self._predict_once(lane.model,
                                                              row))
                except Exception as e:
                    lane.breaker.record_failure()
                    err = e
                    log.warning("solo re-dispatch of %r failed "
                                "(attempt %d/%d): %s", rec.uri, attempt + 1,
                                self.dispatch_retries, e)
                    continue
                lane.breaker.record_success()
                self._emit_dispatch([rec], t1)
                self._pub_put(lane, [rec], preds, t1, row)
                err = None
                break
            if err is not None:
                if budget_refused:
                    log.error("record %r: batch dispatch crashed and the "
                              "retry budget is exhausted; dead-lettering "
                              "without a solo retry", rec.uri)
                else:
                    log.error("record %r crashed dispatch %d time(s); "
                              "dead-lettering", rec.uri,
                              self.dispatch_retries + 1)
                self._m_dead_letter.inc()
                self.metrics.emit("serving.dead_letter", uri=rec.uri,
                                  trace=rec.trace, error=str(err),
                                  model=lane.name)
                # durable: the poison payload spills to the on-disk DLQ
                # (operators replay it after a fix) BEFORE the producer
                # is answered — the answer is a receipt, the spill is
                # the work
                if self._dlq is not None:
                    try:
                        self._dlq.append(rec.uri, row[0], reason="dispatch",
                                         trace=rec.trace, error=str(err),
                                         model=lane.name)
                    except Exception:
                        log.exception("DLQ spill failed for dead-lettered "
                                      "record %r", rec.uri)
                    else:
                        # the landed spill is the settlement: the DLQ
                        # owns the work now, a reclaim must not re-serve
                        # it under the operator's replay
                        self._ack([rec.eid])
                self._record_failure(
                    [rec], parent="dequeue",
                    error="dead-lettered: dispatch crashed repeatedly")

    def _probe_dispatch(self, async_fn, batch, n: int):
        """Non-blocking dispatch probe. Spans cover the MODEL calls only —
        flushing a previous batch has its own serving.flush span and must
        not inflate this batch's dispatch latency; a REFUSED probe is
        discarded so its ~zero duration doesn't halve the apparent
        dispatch time."""
        with span("serving.dispatch", registry=self.metrics,
                  records=n) as sp:
            collect = async_fn(batch, block=False)
            if collect is None:
                sp.discard()
        return collect

    def _emit_dispatch(self, recs, t0: float) -> None:
        """Per-request dispatch phase events: ``dur_s`` is the batch
        assembly+decode time from this record's dequeue to the moment its
        batch entered the model (``t0``), ``batch`` the co-dispatched
        record count — the field that explains a latency outlier caused
        by riding in a large batch. Every successful dispatch also
        deposits into the shared retry budget (when one is attached)."""
        if self._killed:
            return
        if self._retry_budget is not None:
            self._retry_budget.on_success()
        n = len(recs)
        for rec in recs:
            if rec.trace is not None:
                self.metrics.emit("request", phase="dispatch",
                                  trace=rec.trace, uri=rec.uri,
                                  parent="dequeue",
                                  dur_s=max(t0 - rec.t_deq, 0.0), batch=n)

    def _record_failure(self, recs, parent: str = "dequeue",
                        error: str = "inference failed") -> None:
        """Registry + event + addressable error records for a failed batch.
        Every traced record also gets a TERMINAL ``failed`` phase event
        (``parent`` = the last phase that did complete), so a by-trace
        reconstruction never shows a failed request as forever in-flight.
        ``error`` is what the producer's ``query()`` sees AND the event's
        error field — a publish failure must not read as a model error.
        Runs on the serve loop AND the publisher: a result store
        refusing the error write must not kill either thread, and every
        record still gets its terminal event (emitted BEFORE the write,
        so a mid-batch write failure cannot leave later records
        forever in-flight in a by-trace reconstruction). Settlement:
        each record whose error answer LANDED is acked; one whose write
        failed stays pending, so a reclaim re-answers it once the
        store recovers. Callers that already settled entries another
        way (a durable DLQ spill) acked them first — the re-ack here
        removes nothing and counts nothing."""
        if self._killed:
            return
        self._m_failures.inc(len(recs))
        # error-labeled breakdown in its OWN family (a labeled series
        # under zoo_serving_failures_total would double-count every
        # failure in a sum() over the family): the scrape must let an
        # operator tell a backend outage from a broken model without
        # falling back to the event log
        # error = one of the addressable failure strings the server
        # itself writes (see the catalog row) — a closed set
        self.metrics.counter(  # zoolint: disable=ZL015 bounded label set
            "zoo_serving_failure_errors_total",
            "failed records by error kind (model vs result-store)",
            labels={"error": error}).inc(len(recs))
        self.metrics.emit("serving.failure", records=len(recs), error=error)
        answered = []
        for rec in recs:
            if rec.trace is not None:
                self.metrics.emit("request", phase="failed", trace=rec.trace,
                                  uri=rec.uri, parent=parent, error=error)
            try:
                self.backend.set_result(rec.uri, {"error": error})
            except Exception:
                log.exception("error record for %r could not be written "
                              "(backend down?)", rec.uri)
                continue
            answered.append(rec.eid)
        self._ack(answered)

    # -- readback + publish --------------------------------------------------
    def _flush(self, pending: _Pending) -> None:
        """Block on a dispatched batch's readback, then hand the results
        to the async publisher — encode + result-store writes + publish
        bookkeeping happen off the serve loop's critical path. The batch
        arena returns to its lane's pool here: after readback the device
        has fully consumed the input buffer. Bucket-padding rows are
        sliced off the predictions here — they never reach the
        publisher. The publisher queue is bounded, so a stalled result
        backend backpressures the loop instead of buffering
        unboundedly."""
        lane, recs, collect, t0, arena, inputs = pending
        if self._killed:
            # simulated crash: abandon the readback (no publish, no
            # error answer, no ack) — a real SIGKILL would have died
            # holding exactly this in-flight work
            lane.arena_pool.release(arena)
            return
        try:
            with span("serving.flush", registry=self.metrics,
                      records=len(recs)):
                preds = np.asarray(collect())[:len(recs)]
            if arena is not None and np.may_share_memory(preds, arena):
                # a sync model may answer with a VIEW of its input (the
                # server accepts any .predict) — the publisher encodes
                # after this arena is recycled, so aliased predictions
                # must be copied out before release
                preds = preds.copy()
        except Exception:
            lane.breaker.record_failure()
            log.exception("inference failed for %d records; writing errors",
                          len(recs))
            self._record_failure(recs, parent="dispatch")
            return
        finally:
            lane.arena_pool.release(arena)
        # the breaker's success signal: the readback LANDED — for an
        # async model this is where real inference failures would have
        # surfaced, so this (and not dispatch enqueue) is what may reset
        # the consecutive-failure count / close a half-open probe
        lane.breaker.record_success()
        self._pub_put(lane, recs, preds, t0, inputs)

    def _pub_put(self, lane: _Lane, recs, preds, t0: float, inputs) -> None:
        """Hand one batch to the publisher, bounded: a publisher wedged
        on a stalled result store must surface as addressable failures
        (and DLQ spills) after ``_PUB_PUT_TIMEOUT_S``, not park the
        serve loop forever on an unbounded put. The bounded queue is
        still the normal backpressure — the timeout only fires once the
        stall outlasts any healthy drain."""
        try:
            self._pub_queue.put((lane, recs, preds, t0, inputs),
                                timeout=_PUB_PUT_TIMEOUT_S)
        except queue.Full:
            log.error("publisher queue still full after %.0fs (result "
                      "backend stalled?); failing %d record(s) "
                      "addressably", _PUB_PUT_TIMEOUT_S, len(recs))
            self._spill_publish(recs, inputs, error="publish queue full",
                                model=lane.name)
            self._record_failure(recs, parent="dispatch",
                                 error="result publish failed")
            return
        self._m_backlog.set(self._pub_queue.qsize())

    def _spill_publish(self, recs, inputs, error: str,
                       model: Optional[str] = None) -> List[str]:
        """Spill a batch the publisher gave up on to the durable DLQ —
        the original request payloads, so ``zoo-dlq replay`` can re-serve
        them after the result store recovers. No-op without a DLQ (or
        for batches dispatched before one was attached). A landed spill
        IS settlement: the spilled entries are acked out of the group's
        PEL here (the work is durably owned by the DLQ now — a reclaim
        re-serving it would race the operator's replay)."""
        if self._dlq is None or inputs is None:
            return []
        spilled = []
        for i, rec in enumerate(recs):
            try:
                self._dlq.append(rec.uri, inputs[i], reason="publish",
                                 trace=rec.trace, error=error, model=model)
            except Exception:
                log.exception("DLQ spill failed for %r", rec.uri)
                continue
            spilled.append(rec.eid)
        self._ack(spilled)
        return spilled

    def _publisher_loop(self) -> None:
        """The dedicated publisher thread: drains the bounded queue in
        order, publishing each batch. Exits only on the stop sentinel —
        which ``stop()`` enqueues AFTER the serve loop has flushed every
        pending batch, so acked work is never dropped.

        Writes run under the publisher-side circuit breaker: a publish
        failure dead-letters the batch durably (DLQ spill + the distinct
        ``result publish failed`` answer) and counts against the
        breaker; once it trips, queued batches fast-fail straight to the
        DLQ — during a result-store outage the queue drains at spill
        speed instead of one write-timeout per batch, and the half-open
        probe publishes a real batch when the window elapses."""
        q = self._pub_queue
        while True:
            item = q.get()
            if item is _PUB_STOP:
                return
            lane, recs, preds, t0, inputs = item
            if self._killed:
                # simulated crash (kill()): drop without publishing,
                # answering, or acking — the entries stay pending for a
                # surviving replica's reclaim
                self._m_backlog.set(q.qsize())
                continue
            if not self._pub_breaker.allow():
                self._spill_publish(recs, inputs,
                                    error="publish breaker open",
                                    model=lane.name)
                self._record_failure(recs, parent="dispatch",
                                     error="result publish failed")
                self._m_backlog.set(q.qsize())
                continue
            try:
                self._publish(lane, recs, preds, t0)
            except Exception as e:
                # a publish failure must not kill the drain thread —
                # spill durably, then answer the batch with addressable
                # error records so producers fail fast instead of
                # timing out
                self._pub_breaker.record_failure()
                log.exception("publish failed for %d records; writing "
                              "error records", len(recs))
                self._spill_publish(recs, inputs,
                                    error=f"{type(e).__name__}: {e}",
                                    model=lane.name)
                self._record_failure(recs, parent="dispatch",
                                     error="result publish failed")
            else:
                self._pub_breaker.record_success()
            self._m_backlog.set(q.qsize())

    def _publish(self, lane: _Lane, recs, preds, t0: float) -> None:
        """Encode + write one batch's results and do the publish-side
        bookkeeping: counters (records/batches), batch-size, encode and
        dispatch→publish latency histograms, per-record publish phase
        events and e2e quantiles, one ``serving.flush`` JSON event, and
        the TensorBoard scalars. Each result echoes its request's wire
        version — v2 requests get raw-bytes results, v1 requests get the
        base64 ``.npy`` form old consumers decode."""
        # publisher-only fault site: unlike backend.set_results (shared
        # with the shed/error-record writes), a plan here hits exactly
        # the result publishes — the overload-chaos outage window
        faults.inject("serving.publish")
        t_enc = time.perf_counter()
        results = {}
        for i, rec in enumerate(recs):
            if rec.v2:
                results[rec.uri] = encode_tensor(preds[i], key="value")
            else:
                results[rec.uri] = {"value": encode_array(preds[i])}
        self._m_encode.observe(time.perf_counter() - t_enc)
        set_results = getattr(self.backend, "set_results", None)
        if set_results is not None:
            set_results(results)
        else:   # foreign backend without the batched write
            for uri, fields in results.items():
                self.backend.set_result(uri, fields)
        # settlement: the results LANDED — ack the batch out of the
        # group's PEL. Strictly after the publish (the lose-on-crash
        # window this ordering closes); an ack lost here leaves the
        # entries pending and a reclaim re-answers them idempotently —
        # same uri, same prediction, the consumer sees one result.
        self._ack([rec.eid for rec in recs])
        self.served += len(recs)
        self._batches += 1
        now = time.perf_counter()
        now_wall = time.time()
        self._last_flush_wall = now_wall
        latency = max(now - t0, 0.0)
        self._m_records.inc(len(recs))
        lane.m_records.inc(len(recs))
        self._m_batches.inc()
        self._m_batch_size.observe(len(recs))
        self._m_dispatch.observe(latency)
        self._q_dispatch.observe(latency)
        for rec in recs:
            if rec.t_enq is not None:
                # end-to-end = producer enqueue (wall, from the entry id)
                # to publish (wall); clamped — the skew was already
                # counted once at the queue-wait clamp
                self._q_e2e.observe(max(now_wall - rec.t_enq, 0.0))
            if rec.trace is not None:
                self.metrics.emit(
                    "request", phase="publish", trace=rec.trace,
                    uri=rec.uri, parent="dispatch", dur_s=latency,
                    e2e_s=(max(now_wall - rec.t_enq, 0.0)
                           if rec.t_enq is not None else None))
        self.metrics.emit("serving.flush", records=len(recs),
                          batch=self._batches, latency_s=latency,
                          model=lane.name)
        if self._summary is not None:
            t_prev = self._t_last_flush
            self._t_last_flush = now
            # interval start = the later of (previous flush, this batch's
            # dispatch): under continuous load that is the inter-flush
            # interval (steady-state rate, no double-counting the
            # overlapped round-trip); after an idle gap it is this batch's
            # own dispatch→publish time (idle poll time must not read as
            # a throughput collapse)
            start = t0 if t_prev is None else max(t_prev, t0)
            dt = max(now - start, 1e-9)
            self._summary.add_scalar("Serving Throughput", len(recs) / dt,
                                     self._batches)
            self._summary.add_scalar("Serving Records", self.served,
                                     self._batches)
            self._summary.flush()
