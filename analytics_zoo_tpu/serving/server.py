"""Cluster Serving — the serving loop, parity with
``serving/ClusterServing.scala:103-134,243-289`` re-designed for a TPU chip:

* the reference runs a Spark-streaming micro-batch per trigger; here one
  background thread drains the input stream and pushes through a jitted
  ``InferenceModel`` (replica-queue concurrency inside),
* requests are batched up to ``batch_size`` per dispatch — padding to a
  fixed shape inside ``InferenceModel.predict`` keeps ONE compiled program
  regardless of how many requests arrived (dynamic batch sizes would
  recompile per unique size),
* backpressure comes from the bounded stream (``LocalBackend.xadd`` blocks),
  replacing the reference's Redis-memory watermark polling.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Optional

import numpy as np

from ..observability import default_registry, span
from .backend import LocalBackend, default_backend
from .client import INPUT_STREAM, decode_array, encode_array

log = logging.getLogger("analytics_zoo_tpu.serving")

__all__ = ["ClusterServing"]

#: per-request carry-through from stream read to publish: the client's
#: trace id plus the two timestamps later phases diff against. ``t_enq``
#: is WALL epoch seconds (parsed from the ``<epoch_ms>-<seq>`` entry id,
#: the only clock the producer and server share); ``t_deq`` is this
#: process's ``perf_counter`` at read time (monotonic — server-side phase
#: durations must not jump on an NTP step).
_Rec = collections.namedtuple("_Rec", ("uri", "trace", "t_enq", "t_deq"))


class ClusterServing:
    """Owns the serve loop: xread → batched predict → result writes.

    Observability (``docs/guides/OBSERVABILITY.md``): every batch updates
    the ``zoo_serving_*`` metrics in ``registry`` (default: the
    process-wide one) — records/batches/error counters, stream-depth
    gauge, batch-size, queue-wait and dispatch→publish latency histograms
    plus p50/p95/p99 quantile summaries (queue-wait, dispatch, and
    end-to-end) — scrapeable via :meth:`serve_metrics`, which also mounts
    ``/healthz`` and ``/statusz``; :meth:`set_json_events` additionally
    logs one structured JSON event per flush/error and, for every record
    the client stamped with a trace id, parent-linked per-request phase
    events (enqueue→dequeue→dispatch→publish) under that id."""

    def __init__(self, model, backend: Optional[LocalBackend] = None,
                 batch_size: int = 32, stream: str = INPUT_STREAM,
                 block_ms: int = 50, registry=None):
        self.model = model          # InferenceModel (or any .predict(x))
        self.backend = backend if backend is not None else default_backend()
        self.batch_size = int(batch_size)
        self.stream = stream
        self.block_ms = int(block_ms)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.served = 0             # this server's records (tests/ops; the
        #                             registry counters are process-cumulative)
        self._summary = None        # InferenceSummary role (TB scalars)
        self._batches = 0
        self._t_last_flush = None   # throughput-interval anchor
        self.metrics = registry if registry is not None else default_registry()
        m = self.metrics
        self._m_records = m.counter(
            "zoo_serving_records_total", "records answered with a prediction")
        self._m_batches = m.counter(
            "zoo_serving_batches_total", "batches published")
        self._m_undecodable = m.counter(
            "zoo_serving_undecodable_total",
            "records dropped with an undecodable-payload error")
        self._m_failures = m.counter(
            "zoo_serving_failures_total",
            "records answered with an inference-failure error")
        self._m_depth = m.gauge(
            "zoo_serving_stream_depth", "input-stream backlog after a read")
        self._m_batch_size = m.histogram(
            "zoo_serving_batch_size", "records per published batch")
        self._m_queue_wait = m.histogram(
            "zoo_serving_queue_wait_seconds",
            "enqueue to read-off-the-stream wait per record")
        self._m_dispatch = m.histogram(
            "zoo_serving_dispatch_seconds",
            "dispatch to publish latency per batch")
        self._m_skew = m.counter(
            "zoo_serving_clock_skew_total",
            "queue-wait observations clamped to zero because the client "
            "clock ran ahead of the server's")
        # quantile digests alongside the histograms: the octave buckets
        # keep the shape, the summaries answer "what IS p99" exactly
        # enough to hold an SLO against (and merge across replicas)
        self._q_queue_wait = m.summary(
            "zoo_serving_queue_wait_quantiles_seconds",
            "queue-wait p50/p95/p99 per record (quantile digest)")
        self._q_dispatch = m.summary(
            "zoo_serving_dispatch_quantiles_seconds",
            "dispatch to publish p50/p95/p99 per batch (quantile digest)")
        self._q_e2e = m.summary(
            "zoo_serving_e2e_quantiles_seconds",
            "enqueue to publish end-to-end p50/p95/p99 per record "
            "(quantile digest)")
        self._last_flush_wall = None   # epoch s of the newest publish
        self._events = None         # JsonEventSink (set_json_events)
        self._scrape = None         # ScrapeServer (serve_metrics)

    def set_tensorboard(self, log_dir: str,
                        app_name: str = "serving") -> "ClusterServing":
        """Write per-batch "Serving Throughput" / "Serving Records" scalars
        (the reference's throughput-to-TensorBoard path,
        ``ClusterServing.scala:291-317`` + ``InferenceSummary.scala``).
        Call before ``start()`` — swapping the writer under a running
        serve loop would race ``_flush`` on the closed file handle."""
        import os
        from ..utils.tensorboard import EventFileWriter
        if self._thread is not None:    # mirrors start()'s double-start guard
            raise RuntimeError(
                "serving already started; call set_tensorboard() before "
                "start() (or after stop())")
        if self._summary is not None:  # redirecting: release the old fd
            self._summary.close()
        self._summary = EventFileWriter(os.path.join(log_dir, app_name))
        return self

    def set_json_events(self, path: str) -> "ClusterServing":
        """Log one structured JSON event per published batch / error record
        to ``path`` (JSON lines; see OBSERVABILITY.md). The sink is also
        attached to this server's registry, so spans emit there too. Call
        before ``start()``."""
        from ..observability import JsonEventSink
        if self._thread is not None:
            raise RuntimeError(
                "serving already started; call set_json_events() before "
                "start() (or after stop())")
        if self._events is not None:
            self.metrics.remove_event_sink(self._events)
            self._events.close()
        self._events = JsonEventSink(path)
        self.metrics.add_event_sink(self._events)
        return self

    def serve_metrics(self, port: int = 0, host: str = "127.0.0.1"):
        """Mount the observability HTTP endpoint over this server's
        registry — ``/metrics`` (Prometheus exposition), ``/healthz``
        (liveness + serve-loop state), ``/statusz`` (operator page:
        uptime, stream depth, last-flush age, jit-compile totals,
        device info). Returns the :class:`ScrapeServer` (bound port on
        ``.port``); closed automatically by :meth:`stop`. Pretty-print
        it from a shell with ``scripts/cluster-serving-status``.
        ``host="0.0.0.0"`` exposes it to an off-host Prometheus scraper
        (the default binds loopback only)."""
        from ..observability import ScrapeServer
        if self._scrape is not None:
            self._scrape.close()
        self._scrape = ScrapeServer(self.metrics, port=port, host=host,
                                    health_fn=self._health_info)
        return self._scrape

    def _health_info(self) -> dict:
        """Serve-loop introspection for /healthz and /statusz. Runs on
        the scrape thread — reads only cheap fields and the backend's
        stream length (its lock is held per operation, never across a
        dispatch)."""
        age = (None if self._last_flush_wall is None
               else max(time.time() - self._last_flush_wall, 0.0))
        thread = self._thread
        return {"serving": {
            # is_alive, not a None check: a serve loop killed by an
            # escaped exception must read as down — a liveness endpoint
            # that says ok over a dead loop is worse than none
            "running": thread is not None and thread.is_alive(),
            "stream_depth": self.backend.stream_len(self.stream),
            "served": self.served,
            "batches": self._batches,
            "last_flush_age_s": age,
        }}

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ClusterServing":
        if self._thread is not None:
            raise RuntimeError("serving already started")
        self._stop.clear()
        self._t_last_flush = None   # a restart must not span the downtime
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="cluster-serving")
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the loop; with ``drain`` first wait for the stream to empty."""
        if self._thread is None:
            self._close_sinks()
            return
        if drain:
            deadline = time.monotonic() + timeout
            while (self.backend.stream_len(self.stream) > 0
                   and time.monotonic() < deadline):
                time.sleep(0.01)
        self._stop.set()
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            # keep the handle: a discarded live thread would let a second
            # start() race two consumers on the same stream
            raise TimeoutError(
                f"serve loop still running after {timeout}s (model dispatch "
                f"in flight?); call stop() again to re-join")
        self._thread = None
        self._close_sinks()

    def _close_sinks(self) -> None:
        if self._summary is not None:
            self._summary.close()
            self._summary = None
        if self._scrape is not None:
            self._scrape.close()
            self._scrape = None
        if self._events is not None:
            self.metrics.remove_event_sink(self._events)
            self._events.close()
            self._events = None

    # -- the loop -----------------------------------------------------------
    def _loop(self) -> None:
        """Two-deep software pipeline: batch N's device time + dispatch
        round-trip runs while batch N+1 is read and decoded on the host
        (``predict_async`` enqueues the XLA work and defers only the
        readback). On a tunneled/remote device the round-trip dominates
        the batch budget, so overlapping it with host work roughly
        doubles sustainable throughput; one batch in flight + one being
        assembled keeps the memory bound."""
        pending = None   # (recs, collect, t0) — dispatched, readback deferred
        try:
            while not self._stop.is_set():
                entries = self.backend.xread(self.stream, self.batch_size,
                                             block_ms=self.block_ms)
                if not entries:
                    if pending is not None:
                        pending = self._flush(pending)
                    continue
                # ONE stream_len per read feeds both the gauge and the
                # drain checks below — we are the only consumer, so the
                # backlog can only grow between here and those checks
                # (a stale 0 errs toward flushing, never toward parking)
                depth = self.backend.stream_len(self.stream)
                self._m_depth.set(depth)
                now_s = time.time()
                now_p = time.perf_counter()
                recs, tensors = [], []
                for eid, fields in entries:
                    wait, t_enq = self._observe_queue_wait(eid, now_s)
                    try:
                        # uri first: a decodable payload with a missing
                        # uri must not leave an orphan tensor that would
                        # misalign every later uri with the wrong
                        # prediction
                        uri = fields["uri"]
                        arr = decode_array(fields["data"])
                    except Exception:
                        # write an addressable error so the producer's
                        # query() fails fast instead of blocking out its
                        # full timeout
                        log.exception("undecodable record (uri=%r)",
                                      fields.get("uri"))
                        self._m_undecodable.inc()
                        self.metrics.emit("serving.undecodable",
                                          uri=fields.get("uri"),
                                          trace=fields.get("trace"))
                        if fields.get("uri"):
                            self.backend.set_result(
                                fields["uri"],
                                {"error": "undecodable payload"})
                        continue
                    rec = _Rec(uri, fields.get("trace"), t_enq, now_p)
                    if rec.trace is not None:
                        # the request's first two phase events; later
                        # phases (dispatch, publish) link back via the
                        # trace id + parent-phase field
                        self.metrics.emit("request", phase="enqueue",
                                          trace=rec.trace, uri=uri,
                                          parent=None, at_s=t_enq)
                        self.metrics.emit("request", phase="dequeue",
                                          trace=rec.trace, uri=uri,
                                          parent="enqueue", dur_s=wait)
                    recs.append(rec)
                    tensors.append(arr)
                if not recs:
                    # every record in this read was undecodable: the same
                    # drain signal applies — an empty stream means no next
                    # batch will arrive to trigger the pending readback,
                    # so it would otherwise park for up to block_ms
                    if pending is not None and depth == 0:
                        pending = self._flush(pending)
                    continue
                try:
                    batch = np.stack(tensors)
                except ValueError:
                    # ragged shapes can't batch: drain the pipeline, then
                    # serve one by one (rare path, keep it simple)
                    if pending is not None:
                        pending = self._flush(pending)
                    for rec, t in zip(recs, tensors):
                        nxt, _ = self._dispatch([rec], t[None])
                        if nxt is not None:
                            self._flush(nxt)
                    continue
                nxt, pending = self._dispatch(recs, batch, pending)
                if pending is not None:
                    pending = self._flush(pending)
                if nxt is not None and depth == 0:
                    # nothing left queued: the stream is drained and there
                    # is no next batch to overlap with, so deferring this
                    # readback would only add up to block_ms of tail
                    # latency under trickle load (ADVICE round 5). The
                    # queue length is the drain signal — an under-full
                    # read is not (xread returns on FIRST delivery, so
                    # under sustained single-record load more work is
                    # usually queued already and flushing would serialize
                    # the two-deep pipeline), and a final exactly-full
                    # batch with an empty queue must flush too
                    nxt = self._flush(nxt)
                pending = nxt
        finally:
            if pending is not None:
                self._flush(pending)

    def _observe_queue_wait(self, entry_id, now_s: float):
        """Enqueue→read wait from the stream entry id (both backends stamp
        ids as ``<epoch_ms>-<seq>``, the Redis-stream convention).
        Returns ``(wait_s, enqueue_epoch_s)`` for the per-request trace
        events, ``(None, None)`` on a foreign id scheme. A negative wait
        (client clock ahead of the server) clamps to zero and counts in
        ``zoo_serving_clock_skew_total`` instead of polluting the
        distribution with a bogus near-zero-or-negative sample."""
        try:
            enq_ms = int(str(entry_id).split("-", 1)[0])
        except (TypeError, ValueError):
            return None, None   # foreign id scheme: skip, never break loop
        t_enq = enq_ms / 1000.0
        wait = now_s - t_enq
        if wait < 0:
            self._m_skew.inc()
            wait = 0.0
        self._m_queue_wait.observe(wait)
        self._q_queue_wait.observe(wait)
        return wait, t_enq

    def _dispatch(self, recs, batch, pending=None):
        """Enqueue the device work; ((recs, collect, t0), leftover_pending).
        Tries a NON-blocking async dispatch first: with a single replica
        permit (``concurrent_num=1``) dispatching before collecting our
        own pending batch would deadlock, so on a busy model the pending
        batch is flushed (releasing its permit) and the dispatch retried
        blocking. Models without predict_async (the server accepts any
        ``.predict``) compute synchronously — there is nothing to overlap,
        so the pending batch is flushed BEFORE the blocking predict and
        this batch publishes immediately (deferring either one would only
        add latency). Returns (None, pending) when the dispatch failed."""
        t0 = time.perf_counter()
        try:
            # spans cover the MODEL calls only — flushing the previous
            # batch has its own serving.flush span and must not inflate
            # this batch's dispatch latency; a REFUSED non-blocking probe
            # is discarded so its ~zero duration doesn't halve the
            # apparent dispatch time
            async_fn = getattr(self.model, "predict_async", None)
            if async_fn is not None:
                with span("serving.dispatch", registry=self.metrics,
                          records=len(recs)) as sp:
                    collect = async_fn(batch, block=False)
                    if collect is None:
                        sp.discard()
                if collect is None:      # all replica permits in flight
                    if pending is not None:
                        pending = self._flush(pending)
                    with span("serving.dispatch", registry=self.metrics,
                              records=len(recs)):
                        collect = async_fn(batch)
                self._emit_dispatch(recs, t0)
                return (recs, collect, t0), pending
            if pending is not None:
                pending = self._flush(pending)
            with span("serving.dispatch", registry=self.metrics,
                      records=len(recs)):
                preds = self.model.predict(batch)
            self._emit_dispatch(recs, t0)
            self._flush((recs, (lambda: preds), t0))
            return None, pending
        except Exception:
            log.exception("inference dispatch failed for %d records; "
                          "writing errors", len(recs))
            self._record_failure(recs, parent="dequeue")
            return None, pending

    def _emit_dispatch(self, recs, t0: float) -> None:
        """Per-request dispatch phase events: ``dur_s`` is the batch
        assembly+decode time from this record's dequeue to the moment its
        batch entered the model (``t0``), ``batch`` the co-dispatched
        record count — the field that explains a latency outlier caused
        by riding in a large batch."""
        n = len(recs)
        for rec in recs:
            if rec.trace is not None:
                self.metrics.emit("request", phase="dispatch",
                                  trace=rec.trace, uri=rec.uri,
                                  parent="dequeue",
                                  dur_s=max(t0 - rec.t_deq, 0.0), batch=n)

    def _record_failure(self, recs, parent: str = "dequeue") -> None:
        """Registry + event + addressable error records for a failed batch.
        Every traced record also gets a TERMINAL ``failed`` phase event
        (``parent`` = the last phase that did complete), so a by-trace
        reconstruction never shows a failed request as forever in-flight."""
        self._m_failures.inc(len(recs))
        self.metrics.emit("serving.failure", records=len(recs))
        for rec in recs:
            if rec.trace is not None:
                self.metrics.emit("request", phase="failed", trace=rec.trace,
                                  uri=rec.uri, parent=parent)
            self.backend.set_result(rec.uri, {"error": "inference failed"})

    def _flush(self, pending) -> None:
        """Block on a dispatched batch's readback and publish its results.
        Returns None so callers can overwrite their pending slot.

        Bookkeeping is registry-backed: counters (records/batches),
        batch-size and dispatch→publish latency histograms, plus one
        ``serving.flush`` JSON event when a sink is attached. The
        TensorBoard scalars derive from the same measurements."""
        recs, collect, t0 = pending
        try:
            with span("serving.flush", registry=self.metrics,
                      records=len(recs)):
                preds = np.asarray(collect())
        except Exception:
            log.exception("inference failed for %d records; writing errors",
                          len(recs))
            self._record_failure(recs, parent="dispatch")
            return None
        for i, rec in enumerate(recs):
            self.backend.set_result(rec.uri,
                                    {"value": encode_array(preds[i])})
        self.served += len(recs)
        self._batches += 1
        now = time.perf_counter()
        now_wall = time.time()
        self._last_flush_wall = now_wall
        latency = max(now - t0, 0.0)
        self._m_records.inc(len(recs))
        self._m_batches.inc()
        self._m_batch_size.observe(len(recs))
        self._m_dispatch.observe(latency)
        self._q_dispatch.observe(latency)
        for rec in recs:
            if rec.t_enq is not None:
                # end-to-end = producer enqueue (wall, from the entry id)
                # to publish (wall); clamped — the skew was already
                # counted once at the queue-wait clamp
                self._q_e2e.observe(max(now_wall - rec.t_enq, 0.0))
            if rec.trace is not None:
                self.metrics.emit(
                    "request", phase="publish", trace=rec.trace,
                    uri=rec.uri, parent="dispatch", dur_s=latency,
                    e2e_s=(max(now_wall - rec.t_enq, 0.0)
                           if rec.t_enq is not None else None))
        self.metrics.emit("serving.flush", records=len(recs),
                          batch=self._batches, latency_s=latency)
        if self._summary is not None:
            t_prev = self._t_last_flush
            self._t_last_flush = now
            # interval start = the later of (previous flush, this batch's
            # dispatch): under continuous load that is the inter-flush
            # interval (steady-state rate, no double-counting the
            # overlapped round-trip); after an idle gap it is this batch's
            # own dispatch→publish time (idle poll time must not read as
            # a throughput collapse)
            start = t0 if t_prev is None else max(t_prev, t0)
            dt = max(now - start, 1e-9)
            self._summary.add_scalar("Serving Throughput", len(recs) / dt,
                                     self._batches)
            self._summary.add_scalar("Serving Records", self.served,
                                     self._batches)
            self._summary.flush()
        return None
