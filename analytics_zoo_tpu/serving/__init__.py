"""Cluster Serving — L9 of the layer map (SURVEY §1): stream-in/stream-out
model serving with batching and backpressure (``serving/ClusterServing.scala``)."""

from .backend import LocalBackend, QueueFullError, default_backend  # noqa: F401
from .client import InputQueue, OutputQueue, ServingError  # noqa: F401
from .dlq import DeadLetterQueue  # noqa: F401
from .fleet import FleetSaturatedError, FleetView  # noqa: F401
from .server import ClusterServing  # noqa: F401
