"""Fleet coordination — the shared replica registry under one stream.

Every ``ClusterServing`` replica heartbeats a small JSON payload into
its backend's fleet hash (``fleet:<stream>`` on Redis, an in-process
dict on ``LocalBackend``): its serving mode (consumer-group vs legacy
single-consumer), stream depth, pending-entry count, shed watermark,
utilization, and a wall-clock timestamp. Two things read it back:

* **mode guard** — ``ClusterServing.start()`` refuses to join a stream
  another live replica serves in an INCOMPATIBLE mode (a legacy
  consume-on-read server racing a group consumer would double-serve or
  starve it; see ``check_mode_conflict``),
* **fleet backpressure** — ``InputQueue.enqueue`` consults a cached
  :class:`FleetView`: when EVERY live replica reports itself saturated
  (live work — backlog plus its own in-flight pending entries — above
  its shed watermark), the producer is slowed and then
  refused with :class:`FleetSaturatedError` *at enqueue* — upstream of
  the stream — so per-replica shedding (PR 7) becomes the backstop
  instead of the first line of defense.

Staleness is bounded on both axes: a member whose heartbeat is older
than ``ttl_s`` is treated as dead (a killed replica cannot veto or
saturate the fleet forever), and the producer-side view re-reads the
backend at most once per ``cache_s`` (a hot producer loop must not turn
backpressure checks into a backend hammering).
"""

from __future__ import annotations

import json
import logging
import time
from typing import Dict, Optional

log = logging.getLogger("analytics_zoo_tpu.serving.fleet")

__all__ = ["FleetView", "FleetSaturatedError", "publish_member",
           "remove_member", "live_members", "check_mode_conflict",
           "DEFAULT_TTL_S"]

#: a member heartbeat older than this is dead (its replica crashed or
#: was killed without a clean stop) — 3x the default 1 s heartbeat, so
#: one dropped beat never flaps membership
DEFAULT_TTL_S = 3.0


class FleetSaturatedError(RuntimeError):
    """Every live replica reported itself saturated and the enqueue-side
    wait budget elapsed — the fleet-level backpressure refusal."""


def _fleet_surface(backend) -> bool:
    """Duck-typed: a backend participates when it exposes the fleet
    key-value surface (both in-repo backends do; a foreign minimal
    backend silently opts the whole feature out)."""
    return all(hasattr(backend, m)
               for m in ("fleet_set", "fleet_all", "fleet_del"))


def publish_member(backend, stream: str, consumer: str,
                   info: Dict) -> None:
    """One heartbeat: merge ``info`` with a fresh wall-clock stamp and
    write it under this consumer's field. Failures log and drop — a
    backend blip must not crash the serve loop over telemetry."""
    if not _fleet_surface(backend):
        return
    payload = dict(info)
    payload["ts"] = time.time()
    try:
        backend.fleet_set(stream, consumer, json.dumps(payload))
    except Exception as e:
        log.debug("fleet heartbeat for %r failed: %s", consumer, e)


def remove_member(backend, stream: str, consumer: str) -> None:
    """Clean deregistration on stop(); a crash skips this and the TTL
    reaps the stale entry instead."""
    if not _fleet_surface(backend):
        return
    try:
        backend.fleet_del(stream, consumer)
    except Exception as e:
        log.debug("fleet deregistration for %r failed: %s", consumer, e)


def live_members(backend, stream: str,
                 ttl_s: float = DEFAULT_TTL_S) -> Dict[str, Dict]:
    """Members whose heartbeat is fresher than ``ttl_s``; malformed
    payloads are skipped (a half-written heartbeat must not poison the
    view). Entries dead for well past any caller's TTL are reaped from
    the registry here — consumer names are unique per process, so a
    crash-looping replica would otherwise grow the fleet hash by one
    never-deleted field per restart, unbounded (a clean ``stop()``
    deregisters; a crash cannot). Reaping is best-effort and generous
    (``3x max(ttl_s, DEFAULT_TTL_S)``): a replica merely paused never
    loses its slot to a racing reader, and re-registers on its next
    heartbeat even if it does."""
    if not _fleet_surface(backend):
        return {}
    now = time.time()
    reap_after = 3.0 * max(ttl_s, DEFAULT_TTL_S)
    out: Dict[str, Dict] = {}
    reap = []
    for consumer, raw in backend.fleet_all(stream).items():
        try:
            info = json.loads(raw)
            # a JSON-valid non-object (`123`, `"x"` — a foreign writer)
            # is garbage too: .get would raise AttributeError and take
            # every start() on the stream down with it
            if not isinstance(info, dict):
                raise TypeError("heartbeat payload is not an object")
            ts = float(info.get("ts", 0.0))
        except (ValueError, TypeError):
            reap.append(consumer)   # garbage never refreshes itself
            continue
        if now - ts <= ttl_s:
            out[consumer] = info
        elif now - ts > reap_after:
            reap.append(consumer)
    for consumer in reap:
        try:
            backend.fleet_del(stream, consumer)
        except Exception as e:
            log.debug("fleet reap of %r failed: %s", consumer, e)
    return out


def check_mode_conflict(backend, stream: str, consumer: str, mode: str,
                        ttl_s: float = DEFAULT_TTL_S) -> None:
    """Fail LOUDLY when a live peer serves ``stream`` in an incompatible
    mode. ``mode`` is ``"single"`` (legacy consume-on-read) or
    ``"group:<name>"``; any mismatch conflicts — single vs group
    double-serves (the legacy reader pops entries out from under the
    group's delivery accounting), and two different group names would
    compete for pops while each believes it owns a complete PEL. Raised
    at ``start()``, before the first read can do damage (the
    mixed-version fleet guard, docs/guides/SERVING.md rollout
    runbook)."""
    for peer, info in live_members(backend, stream, ttl_s).items():
        if peer == consumer:
            continue
        peer_mode = str(info.get("mode", ""))
        if peer_mode and peer_mode != mode:
            raise RuntimeError(
                f"serving mode conflict on stream {stream!r}: this "
                f"replica ({consumer!r}) would serve in mode {mode!r} but "
                f"live replica {peer!r} serves in mode {peer_mode!r} "
                f"(heartbeat {time.time() - float(info.get('ts', 0.0)):.1f}s "
                f"old). A consume-on-read server and a consumer-group "
                f"server on one stream double-serve or starve each other — "
                f"finish the rollout one mode at a time "
                f"(docs/guides/SERVING.md, fleet rollout runbook)")


class FleetView:
    """Producer-side cached read of the fleet registry.

    ``saturated()`` answers "should this producer back off?": True when
    there is at least one live member AND every live member reports
    ``saturated`` (each replica computes that itself — backlog plus its
    own in-flight pending above its shed watermark). One replica with
    headroom keeps the fleet
    open; zero live members keeps it open too (nothing is served, but
    refusing enqueues on an empty registry would break every
    pre-fleet deployment and test).

    Reads are cached for ``cache_s`` — bounded staleness, not a read
    per enqueue. A backend error reads as "not saturated" (producers
    must never be refused on a telemetry blip; the bounded ``xadd``
    itself still backpressures)."""

    def __init__(self, backend, stream: str, cache_s: float = 0.25,
                 ttl_s: float = DEFAULT_TTL_S):
        self.backend = backend
        self.stream = stream
        self.cache_s = float(cache_s)
        self.ttl_s = float(ttl_s)
        self._cached_at: Optional[float] = None
        self._members: Dict[str, Dict] = {}

    def members(self) -> Dict[str, Dict]:
        now = time.monotonic()
        if self._cached_at is None or now - self._cached_at >= self.cache_s:
            try:
                self._members = live_members(self.backend, self.stream,
                                             self.ttl_s)
            except Exception as e:
                log.debug("fleet read failed (treating as open): %s", e)
                self._members = {}
            self._cached_at = now
        return self._members

    def saturated(self) -> bool:
        members = self.members()
        return bool(members) and all(m.get("saturated")
                                     for m in members.values())
