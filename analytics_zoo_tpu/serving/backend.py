"""Serving queue backends — the transport under Cluster Serving.

The reference couples serving to a Redis instance: producers ``XADD`` to an
input stream, the serving job consumes, and results land as ``result:<uri>``
hashes (``serving/ClusterServing.scala:103-134``; client
``pyzoo/zoo/serving/client.py:58-142``). Here the same stream/result contract
is an interface with two implementations:

* ``LocalBackend`` — in-process, thread-safe, bounded; the default for tests
  and single-host serving (no external service needed on a TPU VM).
* ``RedisBackend`` — the wire-compatible option when a ``redis`` client is
  installed; same xadd/xread/result surface against a real server.

Backpressure is explicit: a bounded input stream makes ``xadd`` block (up to
a timeout) instead of the reference's used_memory-threshold polling.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["LocalBackend", "RedisBackend", "QueueFullError",
           "default_backend"]


class QueueFullError(RuntimeError):
    """Input stream at capacity and the enqueue timeout elapsed."""


_DEFAULT: Optional["LocalBackend"] = None
_DEFAULT_LOCK = threading.Lock()


def default_backend() -> "LocalBackend":
    """The process-wide LocalBackend that default-constructed InputQueue /
    OutputQueue / ClusterServing share — so the no-args client API actually
    communicates (mirroring the reference, where 'default' means the one
    local Redis)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = LocalBackend()
        return _DEFAULT


class LocalBackend:
    """In-process stream + result store with Redis-stream-like semantics."""

    def __init__(self, maxlen: int = 10000):
        self.maxlen = maxlen
        self._streams: Dict[str, List[Tuple[str, dict]]] = {}
        self._results: Dict[str, dict] = {}
        self._lock = threading.Condition()
        self._seq = itertools.count()

    # -- stream ------------------------------------------------------------
    def xadd(self, stream: str, fields: dict,
             timeout: Optional[float] = None) -> str:
        """Append; blocks while the stream holds ``maxlen`` unread entries."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            entries = self._streams.setdefault(stream, [])
            while len(entries) >= self.maxlen:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise QueueFullError(
                        f"stream {stream!r} full ({self.maxlen}); inference "
                        f"is not keeping up — dequeue or raise maxlen")
                self._lock.wait(remaining)
            entry_id = f"{int(time.time() * 1000)}-{next(self._seq)}"
            entries.append((entry_id, dict(fields)))
            self._lock.notify_all()
            return entry_id

    def xread(self, stream: str, count: int,
              block_ms: int = 100) -> List[Tuple[str, dict]]:
        """Pop up to ``count`` entries, waiting up to ``block_ms`` for the
        first (consume-on-read: the serving loop is the only consumer group)."""
        deadline = time.monotonic() + block_ms / 1000.0
        with self._lock:
            entries = self._streams.setdefault(stream, [])
            while not entries:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._lock.wait(remaining)
            out = entries[:count]
            del entries[:count]
            self._lock.notify_all()  # wake blocked producers
            return out

    def stream_len(self, stream: str) -> int:
        with self._lock:
            return len(self._streams.get(stream, []))

    # -- results -----------------------------------------------------------
    def set_result(self, uri: str, fields: dict) -> None:
        with self._lock:
            self._results[uri] = dict(fields)
            self._lock.notify_all()

    def set_results(self, results: Dict[str, dict]) -> None:
        """Publish a whole batch of result records under ONE lock
        acquisition / wakeup — the async publisher's batched write path
        (per-record ``set_result`` costs a lock round-trip and a
        ``notify_all`` each)."""
        if not results:
            return
        with self._lock:
            for uri, fields in results.items():
                self._results[uri] = dict(fields)
            self._lock.notify_all()

    def pop_result(self, uri: str,
                   timeout: Optional[float] = None) -> Optional[dict]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while uri not in self._results:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return None
                self._lock.wait(remaining)
            return self._results.pop(uri)

    def pop_all_results(self) -> Dict[str, dict]:
        with self._lock:
            out, self._results = self._results, {}
            return out


#: wire fields carried as binary end to end (Redis streams/hashes are
#: binary-safe): the v2 tensor payloads. Every other field (uri, trace,
#: dtype, shape, error text) is utf-8 text.
_BINARY_FIELDS = frozenset({"data", "value"})


class RedisBackend:
    """Same contract against a real Redis; keys match the reference: input
    stream entries + ``result:<uri>`` hashes
    (``serving/ClusterServing.scala:103-134``). Uses the redis-py client
    when installed, otherwise the in-repo RESP wire client
    (``serving/resp.py``) — no package dependency to talk to a real
    server. The ``data``/``value`` payload fields round-trip as raw
    bytes (wire-format v2); all other fields are text."""

    def __init__(self, host: str = "localhost", port: int = 6379,
                 maxlen: int = 10000):
        try:
            import redis
            self._r = redis.Redis(host=host, port=port)
        except ImportError:
            from .resp import RespClient
            self._r = RespClient(host=host, port=port)
        self.maxlen = maxlen
        self._last_id: Dict[str, str] = {}

    def xadd(self, stream: str, fields: dict,
             timeout: Optional[float] = None) -> str:
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._r.xlen(stream) >= self.maxlen:
            if deadline is not None and time.monotonic() > deadline:
                raise QueueFullError(f"stream {stream!r} full ({self.maxlen})")
            time.sleep(0.01)
        return self._r.xadd(stream, fields).decode()

    def xread(self, stream: str, count: int,
              block_ms: int = 100) -> List[Tuple[str, dict]]:
        last = self._last_id.get(stream, "0")
        resp = self._r.xread({stream: last}, count=count, block=block_ms)
        out = []
        for _, entries in resp or []:
            for eid, fields in entries:
                eid = eid.decode()
                out.append((eid, self._decode_fields(fields)))
                self._last_id[stream] = eid
                self._r.xdel(stream, eid)
        return out

    @staticmethod
    def _decode_fields(fields: Dict[bytes, bytes]) -> dict:
        """Field decode for stream entries / result hashes: keys are
        always text; payload fields stay bytes (see ``_BINARY_FIELDS``)."""
        out = {}
        for k, v in fields.items():
            key = k.decode()
            out[key] = v if key in _BINARY_FIELDS else v.decode()
        return out

    def stream_len(self, stream: str) -> int:
        return int(self._r.xlen(stream))

    def set_result(self, uri: str, fields: dict) -> None:
        self._r.hset(f"result:{uri}", mapping=fields)

    def set_results(self, results: Dict[str, dict]) -> None:
        """Batched result publish: ONE pipelined round trip for the whole
        batch (both redis-py and the in-repo RESP client expose the
        ``pipeline()`` surface) instead of one HSET round trip per
        record — the async publisher's write path."""
        if not results:
            return
        pipe = self._r.pipeline()
        for uri, fields in results.items():
            pipe.hset(f"result:{uri}", mapping=fields)
        pipe.execute()

    def pop_result(self, uri: str,
                   timeout: Optional[float] = None) -> Optional[dict]:
        deadline = None if timeout is None else time.monotonic() + timeout
        key = f"result:{uri}"
        while True:
            vals = self._r.hgetall(key)
            if vals:
                self._r.delete(key)
                return self._decode_fields(vals)
            if deadline is not None and time.monotonic() > deadline:
                return None
            time.sleep(0.01)

    def pop_all_results(self) -> Dict[str, dict]:
        out = {}
        for key in self._r.keys("result:*"):
            uri = key.decode().split(":", 1)[1]
            res = self.pop_result(uri, timeout=0)
            if res is not None:
                out[uri] = res
        return out
