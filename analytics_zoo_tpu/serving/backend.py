"""Serving queue backends — the transport under Cluster Serving.

The reference couples serving to a Redis instance: producers ``XADD`` to an
input stream, the serving job consumes, and results land as ``result:<uri>``
hashes (``serving/ClusterServing.scala:103-134``; client
``pyzoo/zoo/serving/client.py:58-142``). Here the same stream/result contract
is an interface with two implementations:

* ``LocalBackend`` — in-process, thread-safe, bounded; the default for tests
  and single-host serving (no external service needed on a TPU VM).
* ``RedisBackend`` — the wire-compatible option when a ``redis`` client is
  installed; same xadd/xread/result surface against a real server.

Backpressure is explicit: a bounded input stream makes ``xadd`` block (up to
a timeout) instead of the reference's used_memory-threshold polling.

Reliability (``docs/guides/RELIABILITY.md``): every wait is bounded — a
``timeout=None`` falls back to the backend's ``default_timeout`` instead
of spinning forever, and the Redis-side polls (full-stream wait, result
wait) back off through ``common.reliability.RetryPolicy`` rather than a
fixed 10 ms spin. Both backends carry named fault-injection sites
(``common.faults``: ``backend.xadd`` / ``backend.xread`` /
``backend.stream_len`` / ``backend.set_result`` / ``backend.set_results``)
so the chaos tests can kill a "connection" deterministically mid-serve.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..common import faults
from ..common.reliability import RetryPolicy

__all__ = ["LocalBackend", "RedisBackend", "QueueFullError",
           "default_backend"]

#: bound applied when a caller passes ``timeout=None`` — an unbounded
#: producer/consumer wait turns a dead serve loop into a hung client
_DEFAULT_TIMEOUT = 30.0


class QueueFullError(RuntimeError):
    """Input stream at capacity and the enqueue timeout elapsed."""


_DEFAULT: Optional["LocalBackend"] = None
_DEFAULT_LOCK = threading.Lock()


def default_backend() -> "LocalBackend":
    """The process-wide LocalBackend that default-constructed InputQueue /
    OutputQueue / ClusterServing share — so the no-args client API actually
    communicates (mirroring the reference, where 'default' means the one
    local Redis)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = LocalBackend()
        return _DEFAULT


class LocalBackend:
    """In-process stream + result store with Redis-stream-like semantics.

    Waits are condition-based (no polling) and BOUNDED: ``timeout=None``
    means ``default_timeout``, not forever — ``xadd`` raises
    ``QueueFullError`` and ``pop_result`` returns None once it elapses.
    """

    def __init__(self, maxlen: int = 10000,
                 default_timeout: float = _DEFAULT_TIMEOUT):
        self.maxlen = maxlen
        self.default_timeout = default_timeout
        self._streams: Dict[str, List[Tuple[str, dict]]] = {}
        self._results: Dict[str, dict] = {}
        self._lock = threading.Condition()
        self._seq = itertools.count()

    # -- stream ------------------------------------------------------------
    def xadd(self, stream: str, fields: dict,
             timeout: Optional[float] = None) -> str:
        """Append; blocks while the stream holds ``maxlen`` unread entries."""
        faults.inject("backend.xadd")
        timeout = self.default_timeout if timeout is None else timeout
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            entries = self._streams.setdefault(stream, [])
            while len(entries) >= self.maxlen:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise QueueFullError(
                        f"stream {stream!r} full ({self.maxlen}); inference "
                        f"is not keeping up — dequeue or raise maxlen")
                self._lock.wait(remaining)
            entry_id = f"{int(time.time() * 1000)}-{next(self._seq)}"
            entries.append((entry_id, dict(fields)))
            self._lock.notify_all()
            return entry_id

    def xread(self, stream: str, count: int,
              block_ms: int = 100) -> List[Tuple[str, dict]]:
        """Pop up to ``count`` entries, waiting up to ``block_ms`` for the
        first (consume-on-read: the serving loop is the only consumer group)."""
        faults.inject("backend.xread")
        deadline = time.monotonic() + block_ms / 1000.0
        with self._lock:
            entries = self._streams.setdefault(stream, [])
            while not entries:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._lock.wait(remaining)
            out = entries[:count]
            del entries[:count]
            self._lock.notify_all()  # wake blocked producers
            return out

    def stream_len(self, stream: str) -> int:
        faults.inject("backend.stream_len")
        with self._lock:
            return len(self._streams.get(stream, []))

    # -- results -----------------------------------------------------------
    def set_result(self, uri: str, fields: dict) -> None:
        faults.inject("backend.set_result")
        with self._lock:
            self._results[uri] = dict(fields)
            self._lock.notify_all()

    def set_results(self, results: Dict[str, dict]) -> None:
        """Publish a whole batch of result records under ONE lock
        acquisition / wakeup — the async publisher's batched write path
        (per-record ``set_result`` costs a lock round-trip and a
        ``notify_all`` each)."""
        if not results:
            return
        spec = faults.inject("backend.set_results")
        if spec is not None and spec.kind == "partial_write":
            # the injected mid-write crash: apply a prefix of the batch,
            # then fail like a dropped connection would
            uris = list(results)
            keep = uris[:max(int(len(uris) * spec.fraction), 0)]
            with self._lock:
                for uri in keep:
                    self._results[uri] = dict(results[uri])
                self._lock.notify_all()
            raise ConnectionError(
                f"injected partial write: {len(keep)}/{len(uris)} applied")
        with self._lock:
            for uri, fields in results.items():
                self._results[uri] = dict(fields)
            self._lock.notify_all()

    def pop_result(self, uri: str,
                   timeout: Optional[float] = None) -> Optional[dict]:
        timeout = self.default_timeout if timeout is None else timeout
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while uri not in self._results:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return None
                self._lock.wait(remaining)
            return self._results.pop(uri)

    def pop_all_results(self) -> Dict[str, dict]:
        with self._lock:
            out, self._results = self._results, {}
            return out


#: wire fields carried as binary end to end (Redis streams/hashes are
#: binary-safe): the v2 tensor payloads. Every other field (uri, trace,
#: dtype, shape, error text) is utf-8 text.
_BINARY_FIELDS = frozenset({"data", "value"})


class RedisBackend:
    """Same contract against a real Redis; keys match the reference: input
    stream entries + ``result:<uri>`` hashes
    (``serving/ClusterServing.scala:103-134``). Uses the redis-py client
    when installed, otherwise the in-repo RESP wire client
    (``serving/resp.py``) — no package dependency to talk to a real
    server. The ``data``/``value`` payload fields round-trip as raw
    bytes (wire-format v2); all other fields are text.

    The full-stream and result waits poll with jittered backoff through
    ``poll_policy`` (no fixed-interval spin hammering the server) and
    are bounded by ``default_timeout`` when the caller passes no
    timeout. Transport-level reconnects live one layer down, in the
    RESP client (``serving/resp.py``)."""

    def __init__(self, host: str = "localhost", port: int = 6379,
                 maxlen: int = 10000,
                 default_timeout: float = _DEFAULT_TIMEOUT,
                 poll_policy: Optional[RetryPolicy] = None):
        try:
            import redis
            self._r = redis.Redis(host=host, port=port)
            # redis-py's transport errors subclass RedisError, NOT the
            # builtin ConnectionError — normalize them (see _call) or the
            # breaker/retry classification upstream never engages
            self._driver_errors: Tuple[type, ...] = (
                redis.exceptions.ConnectionError,
                redis.exceptions.TimeoutError)
        except ImportError:
            from .resp import RespClient
            self._r = RespClient(host=host, port=port)
            self._driver_errors = ()    # RespClient raises builtins already
        self.maxlen = maxlen
        self.default_timeout = default_timeout
        #: backoff for the client-side polls (full stream, result wait):
        #: starts near the old 10 ms spin, backs off to 50 ms so a long
        #: wait costs dozens of round trips, not thousands
        self.poll_policy = poll_policy if poll_policy is not None \
            else RetryPolicy(base_delay=0.005, max_delay=0.05)
        self._last_id: Dict[str, str] = {}

    def _call(self, fn, *args, **kwargs):
        """One driver call with driver-specific transport exceptions
        normalized to the builtin ``ConnectionError`` the reliability
        layer (serve-loop breaker, retry classification) keys on."""
        try:
            return fn(*args, **kwargs)
        except self._driver_errors as e:
            raise ConnectionError(f"{type(e).__name__}: {e}") from e

    def xadd(self, stream: str, fields: dict,
             timeout: Optional[float] = None) -> str:
        # same named fault site as LocalBackend.xadd, so the chaos
        # scenarios in test_chaos.py can also run against a live Redis
        faults.inject("backend.xadd")
        timeout = self.default_timeout if timeout is None else timeout
        if not self.poll_policy.wait_for(
                lambda: self._call(self._r.xlen, stream) < self.maxlen,
                timeout=timeout):
            raise QueueFullError(
                f"stream {stream!r} full ({self.maxlen}); inference is "
                f"not keeping up — dequeue or raise maxlen")
        return self._call(self._r.xadd, stream, fields).decode()

    def xread(self, stream: str, count: int,
              block_ms: int = 100) -> List[Tuple[str, dict]]:
        last = self._last_id.get(stream, "0")
        resp = self._call(self._r.xread, {stream: last}, count=count,
                          block=block_ms)
        out = []
        for _, entries in resp or []:
            for eid, fields in entries:
                eid = eid.decode()
                out.append((eid, self._decode_fields(fields)))
                self._last_id[stream] = eid
                self._call(self._r.xdel, stream, eid)
        return out

    @staticmethod
    def _decode_fields(fields: Dict[bytes, bytes]) -> dict:
        """Field decode for stream entries / result hashes: keys are
        always text; payload fields stay bytes (see ``_BINARY_FIELDS``)."""
        out = {}
        for k, v in fields.items():
            key = k.decode()
            out[key] = v if key in _BINARY_FIELDS else v.decode()
        return out

    def stream_len(self, stream: str) -> int:
        return int(self._call(self._r.xlen, stream))

    def set_result(self, uri: str, fields: dict) -> None:
        self._call(self._r.hset, f"result:{uri}", mapping=fields)

    def set_results(self, results: Dict[str, dict]) -> None:
        """Batched result publish: ONE pipelined round trip for the whole
        batch (both redis-py and the in-repo RESP client expose the
        ``pipeline()`` surface) instead of one HSET round trip per
        record — the async publisher's write path."""
        if not results:
            return
        pipe = self._r.pipeline()
        for uri, fields in results.items():
            pipe.hset(f"result:{uri}", mapping=fields)
        self._call(pipe.execute)

    def pop_result(self, uri: str,
                   timeout: Optional[float] = None) -> Optional[dict]:
        timeout = self.default_timeout if timeout is None else timeout
        key = f"result:{uri}"
        found: List[Dict[bytes, bytes]] = []

        def check() -> bool:
            vals = self._call(self._r.hgetall, key)
            if vals:
                found.append(vals)
                return True
            return False

        if not self.poll_policy.wait_for(check, timeout=timeout):
            return None
        self._call(self._r.delete, key)
        return self._decode_fields(found[0])

    def pop_all_results(self) -> Dict[str, dict]:
        out = {}
        for key in self._call(self._r.keys, "result:*"):
            uri = key.decode().split(":", 1)[1]
            res = self.pop_result(uri, timeout=0)
            if res is not None:
                out[uri] = res
        return out
