"""Serving queue backends — the transport under Cluster Serving.

The reference couples serving to a Redis instance: producers ``XADD`` to an
input stream, the serving job consumes, and results land as ``result:<uri>``
hashes (``serving/ClusterServing.scala:103-134``; client
``pyzoo/zoo/serving/client.py:58-142``). Here the same stream/result contract
is an interface with two implementations:

* ``LocalBackend`` — in-process, thread-safe, bounded; the default for tests
  and single-host serving (no external service needed on a TPU VM).
* ``RedisBackend`` — the wire-compatible option when a ``redis`` client is
  installed; same xadd/xread/result surface against a real server.

Backpressure is explicit: a bounded input stream makes ``xadd`` block (up to
a timeout) instead of the reference's used_memory-threshold polling.

Reliability (``docs/guides/RELIABILITY.md``): every wait is bounded — a
``timeout=None`` falls back to the backend's ``default_timeout`` instead
of spinning forever, and the Redis-side polls (full-stream wait, result
wait) back off through ``common.reliability.RetryPolicy`` rather than a
fixed 10 ms spin. Both backends carry named fault-injection sites
(``common.faults``: ``backend.xadd`` / ``backend.xread`` /
``backend.stream_len`` / ``backend.set_result`` / ``backend.set_results``
/ ``backend.xack`` / ``backend.xclaim``)
so the chaos tests can kill a "connection" deterministically mid-serve.

Consumer groups (the fleet data plane, ``docs/guides/SERVING.md``):
``xreadgroup`` delivers each entry to exactly ONE named consumer of a
group and tracks it in the group's pending-entries set (PEL) until
``xack`` settles it; ``xautoclaim`` lets a survivor take over a dead
peer's pending entries once their idle time passes a threshold. The
legacy ``xread`` (consume-on-read, single consumer) is unchanged — but
an entry it consumes leaves no pending record, so a consumer crash
between read and publish loses it; group mode is how that window
closes. Both backends implement the same surface: ``LocalBackend``
natively, ``RedisBackend`` on real Redis group commands (XGROUP /
XREADGROUP / XACK / XPENDING / XCLAIM). A small fleet key-value
surface (``fleet_set`` / ``fleet_all`` / ``fleet_del``) carries
replica heartbeats for fleet backpressure (``serving/fleet.py``).
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..common import faults
from ..common.reliability import RetryPolicy

__all__ = ["LocalBackend", "RedisBackend", "QueueFullError",
           "default_backend"]

#: bound applied when a caller passes ``timeout=None`` — an unbounded
#: producer/consumer wait turns a dead serve loop into a hung client
_DEFAULT_TIMEOUT = 30.0


class QueueFullError(RuntimeError):
    """Input stream at capacity and the enqueue timeout elapsed."""


class _PendingEntry:
    """One delivered-but-unacked entry in a group's PEL: who owns it,
    since when (monotonic), and how many times it has been delivered
    (first read + every reclaim)."""

    __slots__ = ("fields", "consumer", "delivered_at", "delivery_count")

    def __init__(self, fields: dict, consumer: str):
        self.fields = fields
        self.consumer = consumer
        self.delivered_at = time.monotonic()
        self.delivery_count = 1


_DEFAULT: Optional["LocalBackend"] = None
_DEFAULT_LOCK = threading.Lock()


def default_backend() -> "LocalBackend":
    """The process-wide LocalBackend that default-constructed InputQueue /
    OutputQueue / ClusterServing share — so the no-args client API actually
    communicates (mirroring the reference, where 'default' means the one
    local Redis)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = LocalBackend()
        return _DEFAULT


class LocalBackend:
    """In-process stream + result store with Redis-stream-like semantics.

    Waits are condition-based (no polling) and BOUNDED: ``timeout=None``
    means ``default_timeout``, not forever — ``xadd`` raises
    ``QueueFullError`` and ``pop_result`` returns None once it elapses.
    """

    def __init__(self, maxlen: int = 10000,
                 default_timeout: float = _DEFAULT_TIMEOUT):
        self.maxlen = maxlen
        self.default_timeout = default_timeout
        self._streams: Dict[str, List[Tuple[str, dict]]] = {}
        self._results: Dict[str, dict] = {}
        #: (stream, group) -> ordered PEL: entry id -> _PendingEntry
        self._pending: Dict[Tuple[str, str],
                            "collections.OrderedDict[str, _PendingEntry]"] \
            = {}
        #: stream -> {consumer: json payload} — replica heartbeats
        self._fleet: Dict[str, Dict[str, str]] = {}
        self._lock = threading.Condition()
        self._seq = itertools.count()

    # -- stream ------------------------------------------------------------
    def _outstanding(self, stream: str, entries: List) -> int:
        """Total live work for one stream: undelivered backlog plus
        every group's delivered-but-unacked entries. This is what
        ``maxlen`` bounds — on real Redis XLEN counts in-flight entries
        too, so a consumer that reads but never settles (result store
        down) must still backpressure producers rather than let the PEL
        grow without bound. Caller holds the lock."""
        return len(entries) + sum(len(pel)
                                  for (s, _), pel in self._pending.items()
                                  if s == stream)

    def xadd(self, stream: str, fields: dict,
             timeout: Optional[float] = None) -> str:
        """Append; blocks while the stream holds ``maxlen`` unsettled
        entries (unread backlog + in-flight PEL, matching XLEN)."""
        faults.inject("backend.xadd")
        timeout = self.default_timeout if timeout is None else timeout
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            entries = self._streams.setdefault(stream, [])
            while self._outstanding(stream, entries) >= self.maxlen:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise QueueFullError(
                        f"stream {stream!r} full ({self.maxlen}); inference "
                        f"is not keeping up — dequeue or raise maxlen")
                self._lock.wait(remaining)
            entry_id = f"{int(time.time() * 1000)}-{next(self._seq)}"
            entries.append((entry_id, dict(fields)))
            self._lock.notify_all()
            return entry_id

    def xread(self, stream: str, count: int,
              block_ms: int = 100) -> List[Tuple[str, dict]]:
        """Pop up to ``count`` entries, waiting up to ``block_ms`` for the
        first (consume-on-read: the serving loop is the only consumer group)."""
        faults.inject("backend.xread")
        deadline = time.monotonic() + block_ms / 1000.0
        with self._lock:
            entries = self._streams.setdefault(stream, [])
            while not entries:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._lock.wait(remaining)
            out = entries[:count]
            del entries[:count]
            self._lock.notify_all()  # wake blocked producers
            return out

    # -- consumer groups ----------------------------------------------------
    def xgroup_create(self, stream: str, group: str) -> None:
        """Idempotent: creating a group that exists is a no-op (the Redis
        BUSYGROUP reply is likewise swallowed in ``RedisBackend``)."""
        with self._lock:
            self._pending.setdefault((stream, group), collections.OrderedDict())
            self._streams.setdefault(stream, [])

    def xreadgroup(self, stream: str, group: str, consumer: str, count: int,
                   block_ms: int = 100) -> List[Tuple[str, dict]]:
        """Deliver up to ``count`` undelivered entries to ``consumer``,
        tracking each in the group's PEL until :meth:`xack`. Fires the
        same ``backend.xread`` fault site as :meth:`xread` — one site per
        loop read, whichever mode the server runs in."""
        faults.inject("backend.xread")
        deadline = time.monotonic() + block_ms / 1000.0
        with self._lock:
            pel = self._pending.setdefault((stream, group),
                                           collections.OrderedDict())
            entries = self._streams.setdefault(stream, [])
            while not entries:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._lock.wait(remaining)
            out = entries[:count]
            del entries[:count]
            for eid, fields in out:
                pel[eid] = _PendingEntry(fields, consumer)
            self._lock.notify_all()  # wake blocked producers
            return out

    def xack(self, stream: str, group: str, *entry_ids: str) -> int:
        """Settle delivered entries: remove them from the group's PEL.
        Idempotent — acking a gone id counts 0. Returns how many were
        actually removed."""
        faults.inject("backend.xack")
        removed = 0
        with self._lock:
            pel = self._pending.get((stream, group))
            if pel:
                for eid in entry_ids:
                    removed += pel.pop(eid, None) is not None
            if removed:
                self._lock.notify_all()  # settlement frees xadd capacity
        return removed

    def xautoclaim(self, stream: str, group: str, consumer: str,
                   min_idle_ms: float, count: int = 100
                   ) -> List[Tuple[str, dict, str, int]]:
        """Transfer ownership of up to ``count`` pending entries whose
        idle time passed ``min_idle_ms`` to ``consumer`` (oldest first;
        redis XAUTOCLAIM semantics: the claimer may be the current owner
        — a replica re-claims its OWN entries after a lost reply). The
        claim resets the idle clock and bumps the delivery count, so two
        racing survivors can never both win one entry. Returns
        ``[(entry_id, fields, previous_consumer, delivery_count), ...]``."""
        faults.inject("backend.xclaim")
        now = time.monotonic()
        claimed = []
        with self._lock:
            pel = self._pending.get((stream, group))
            if pel:
                for eid, pe in pel.items():
                    if len(claimed) >= count:
                        break
                    if (now - pe.delivered_at) * 1000.0 < min_idle_ms:
                        continue
                    prev = pe.consumer
                    pe.consumer = consumer
                    pe.delivered_at = now
                    pe.delivery_count += 1
                    claimed.append((eid, pe.fields, prev, pe.delivery_count))
        return claimed

    def xpending(self, stream: str, group: str) -> Dict[str, int]:
        """Per-consumer pending-entry counts for one group (the scaling
        signal on /statusz and the chaos tests' kill-window census)."""
        out: Dict[str, int] = {}
        with self._lock:
            for pe in self._pending.get((stream, group), {}).values():
                out[pe.consumer] = out.get(pe.consumer, 0) + 1
        return out

    def pending_len(self, stream: str, group: str) -> int:
        with self._lock:
            return len(self._pending.get((stream, group), {}))

    def backlog_len(self, stream: str, group: Optional[str] = None) -> int:
        """Entries a new read would see (undelivered backlog). For
        ``LocalBackend`` this equals :meth:`stream_len` — delivered
        entries left the stream list for the PEL; the ``group`` arg
        exists for signature parity with ``RedisBackend``, where XLEN
        still counts delivered-but-unacked entries."""
        with self._lock:
            return len(self._streams.get(stream, []))

    # -- fleet key-value (replica heartbeats, serving/fleet.py) -------------
    def fleet_set(self, stream: str, consumer: str, payload: str) -> None:
        with self._lock:
            self._fleet.setdefault(stream, {})[consumer] = str(payload)

    def fleet_all(self, stream: str) -> Dict[str, str]:
        with self._lock:
            return dict(self._fleet.get(stream, {}))

    def fleet_del(self, stream: str, consumer: str) -> None:
        with self._lock:
            self._fleet.get(stream, {}).pop(consumer, None)

    def stream_len(self, stream: str) -> int:
        faults.inject("backend.stream_len")
        with self._lock:
            return len(self._streams.get(stream, []))

    # -- results -----------------------------------------------------------
    def set_result(self, uri: str, fields: dict) -> None:
        faults.inject("backend.set_result")
        with self._lock:
            self._results[uri] = dict(fields)
            self._lock.notify_all()

    def set_results(self, results: Dict[str, dict]) -> None:
        """Publish a whole batch of result records under ONE lock
        acquisition / wakeup — the async publisher's batched write path
        (per-record ``set_result`` costs a lock round-trip and a
        ``notify_all`` each)."""
        if not results:
            return
        spec = faults.inject("backend.set_results")
        if spec is not None and spec.kind == "partial_write":
            # the injected mid-write crash: apply a prefix of the batch,
            # then fail like a dropped connection would
            uris = list(results)
            keep = uris[:max(int(len(uris) * spec.fraction), 0)]
            with self._lock:
                for uri in keep:
                    self._results[uri] = dict(results[uri])
                self._lock.notify_all()
            raise ConnectionError(
                f"injected partial write: {len(keep)}/{len(uris)} applied")
        with self._lock:
            for uri, fields in results.items():
                self._results[uri] = dict(fields)
            self._lock.notify_all()

    def pop_result(self, uri: str,
                   timeout: Optional[float] = None) -> Optional[dict]:
        timeout = self.default_timeout if timeout is None else timeout
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while uri not in self._results:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return None
                self._lock.wait(remaining)
            return self._results.pop(uri)

    def pop_all_results(self) -> Dict[str, dict]:
        with self._lock:
            out, self._results = self._results, {}
            return out


#: wire fields carried as binary end to end (Redis streams/hashes are
#: binary-safe): the v2 tensor payloads. Every other field (uri, trace,
#: dtype, shape, error text) is utf-8 text.
_BINARY_FIELDS = frozenset({"data", "value"})


class RedisBackend:
    """Same contract against a real Redis; keys match the reference: input
    stream entries + ``result:<uri>`` hashes
    (``serving/ClusterServing.scala:103-134``). Uses the redis-py client
    when installed, otherwise the in-repo RESP wire client
    (``serving/resp.py``) — no package dependency to talk to a real
    server. The ``data``/``value`` payload fields round-trip as raw
    bytes (wire-format v2); all other fields are text.

    The full-stream and result waits poll with jittered backoff through
    ``poll_policy`` (no fixed-interval spin hammering the server) and
    are bounded by ``default_timeout`` when the caller passes no
    timeout. Transport-level reconnects live one layer down, in the
    RESP client (``serving/resp.py``)."""

    def __init__(self, host: str = "localhost", port: int = 6379,
                 maxlen: int = 10000,
                 default_timeout: float = _DEFAULT_TIMEOUT,
                 poll_policy: Optional[RetryPolicy] = None):
        try:
            import redis
            self._r = redis.Redis(host=host, port=port)
            # redis-py's transport errors subclass RedisError, NOT the
            # builtin ConnectionError — normalize them (see _call) or the
            # breaker/retry classification upstream never engages
            self._driver_errors: Tuple[type, ...] = (
                redis.exceptions.ConnectionError,
                redis.exceptions.TimeoutError)
            self._is_resp = False
        except ImportError:
            from .resp import RespClient
            self._r = RespClient(host=host, port=port)
            self._driver_errors = ()    # RespClient raises builtins already
            self._is_resp = True
        self.maxlen = maxlen
        self.default_timeout = default_timeout
        #: backoff for the client-side polls (full stream, result wait):
        #: starts near the old 10 ms spin, backs off to 50 ms so a long
        #: wait costs dozens of round trips, not thousands
        self.poll_policy = poll_policy if poll_policy is not None \
            else RetryPolicy(base_delay=0.005, max_delay=0.05)
        self._last_id: Dict[str, str] = {}
        #: (stream, group) -> (monotonic, total) — bounds the XPENDING
        #: summaries behind the serve loop's depth probes (pre-read shed
        #: check, post-read gauge, heartbeat — each would otherwise be
        #: its own round trip). Invalidated on this instance's own
        #: reads/acks/claims, so local accounting stays exact; other
        #: replicas' settlements surface within the window
        self._pending_cache: Dict[Tuple[str, str], Tuple[float, int]] = {}
        self.pending_cache_s = 0.25

    def _call(self, fn, *args, **kwargs):
        """One driver call with driver-specific transport exceptions
        normalized to the builtin ``ConnectionError`` the reliability
        layer (serve-loop breaker, retry classification) keys on."""
        try:
            return fn(*args, **kwargs)
        except self._driver_errors as e:
            raise ConnectionError(f"{type(e).__name__}: {e}") from e

    def xadd(self, stream: str, fields: dict,
             timeout: Optional[float] = None) -> str:
        # same named fault site as LocalBackend.xadd, so the chaos
        # scenarios in test_chaos.py can also run against a live Redis
        faults.inject("backend.xadd")
        timeout = self.default_timeout if timeout is None else timeout
        if not self.poll_policy.wait_for(
                lambda: self._call(self._r.xlen, stream) < self.maxlen,
                timeout=timeout):
            raise QueueFullError(
                f"stream {stream!r} full ({self.maxlen}); inference is "
                f"not keeping up — dequeue or raise maxlen")
        return self._call(self._r.xadd, stream, fields).decode()

    def xread(self, stream: str, count: int,
              block_ms: int = 100) -> List[Tuple[str, dict]]:
        last = self._last_id.get(stream, "0")
        resp = self._call(self._r.xread, {stream: last}, count=count,
                          block=block_ms)
        out = []
        for _, entries in resp or []:
            for eid, fields in entries:
                eid = eid.decode()
                out.append((eid, self._decode_fields(fields)))
                self._last_id[stream] = eid
                self._call(self._r.xdel, stream, eid)
        return out

    # -- consumer groups ----------------------------------------------------
    def xgroup_create(self, stream: str, group: str) -> None:
        """XGROUP CREATE (from id 0, MKSTREAM); an already-existing
        group's BUSYGROUP reply is swallowed — creation is idempotent."""
        try:
            if self._is_resp:
                self._call(self._r.xgroup_create, stream, group)
            else:
                self._call(self._r.xgroup_create, stream, group, id="0",
                           mkstream=True)
        except ConnectionError:
            raise
        except Exception as e:
            if "BUSYGROUP" not in str(e):
                raise

    def xreadgroup(self, stream: str, group: str, consumer: str, count: int,
                   block_ms: int = 100) -> List[Tuple[str, dict]]:
        """XREADGROUP ``>``: deliver new entries to this consumer, into
        the group's PEL. Entries stay in the stream until the post-
        settlement :meth:`xack` deletes them. Same fault site as
        :meth:`xread` — one ``backend.xread`` per loop read."""
        faults.inject("backend.xread")
        resp = self._call(self._r.xreadgroup, group, consumer,
                          {stream: ">"}, count=count, block=block_ms)
        out = []
        for _, entries in resp or []:
            for eid, fields in entries:
                out.append((eid.decode() if isinstance(eid, bytes) else eid,
                            self._decode_fields(fields)))
        if out:
            self._pending_cache.pop((stream, group), None)
        return out

    def xack(self, stream: str, group: str, *entry_ids: str) -> int:
        """XDEL + XACK: settle the entries AND delete them from the
        stream, so XLEN tracks live work (undelivered + in-flight), not
        history. Both halves are idempotent — a re-ack counts 0.

        XDEL runs FIRST: a connection dropped between the two commands
        then leaves a stream-deleted entry still pending, which the next
        reclaim sweep finds and settles (:meth:`xautoclaim` acks
        nil-field tombstones). The reverse order would leak permanently
        — an acked-but-undeleted entry has left the PEL, is never
        redelivered (the group's last-delivered id already passed it),
        and occupies XLEN/maxlen capacity forever."""
        faults.inject("backend.xack")
        if not entry_ids:
            return 0
        self._call(self._r.xdel, stream, *entry_ids)
        n = int(self._call(self._r.xack, stream, group, *entry_ids))
        self._pending_cache.pop((stream, group), None)
        return n

    def xautoclaim(self, stream: str, group: str, consumer: str,
                   min_idle_ms: float, count: int = 100
                   ) -> List[Tuple[str, dict, str, int]]:
        """Survivor-side reclaim: XPENDING (idle-filtered, with owner and
        delivery count) then XCLAIM the candidate ids. XCLAIM only
        returns entries actually transferred — a racing survivor's claim
        reset their idle clock, so exactly one claimer wins each entry.
        Returns ``[(id, fields, previous_consumer, delivery_count)]``."""
        faults.inject("backend.xclaim")
        min_idle = int(min_idle_ms)
        if self._is_resp:
            pend = self._call(self._r.xpending_range, stream, group,
                              min_idle, count)
        else:
            pend = [(p["message_id"], p["consumer"], p["times_delivered"])
                    for p in self._call(
                        self._r.xpending_range, stream, group, min="-",
                        max="+", count=count, idle=min_idle)]
        if not pend:
            return []
        owners = {self._as_text(eid): (self._as_text(owner), int(times))
                  for eid, owner, times in pend}
        claimed = self._call(self._r.xclaim, stream, group, consumer,
                             min_idle, list(owners))
        self._pending_cache.pop((stream, group), None)
        out = []
        tombstones = []
        for eid, fields in claimed or []:
            eid = self._as_text(eid)
            if fields is None:
                # the message is gone from the stream (an ack whose
                # connection dropped between XDEL and XACK, or trimming):
                # nothing is left to re-answer, so settle the dangling
                # PEL entry instead of re-claiming it every sweep
                tombstones.append(eid)
                continue
            prev, times = owners.get(eid, ("?", 0))
            out.append((eid, self._decode_fields(fields), prev, times + 1))
        if tombstones:
            try:
                self._call(self._r.xack, stream, group, *tombstones)
            except (ConnectionError, OSError):
                pass            # the next sweep retries the settlement
        return out

    def xpending(self, stream: str, group: str) -> Dict[str, int]:
        """Per-consumer pending counts from the XPENDING summary form."""
        if self._is_resp:
            return self._call(self._r.xpending_summary, stream, group)
        info = self._call(self._r.xpending, stream, group)
        return {self._as_text(c["name"]): int(c["pending"])
                for c in (info.get("consumers") or [])}

    def pending_len(self, stream: str, group: str) -> int:
        """Total PEL size, cached for ``pending_cache_s`` (the depth
        probes behind shed checks / gauges / heartbeats call this up to
        several times per serve-loop iteration; each miss is an XPENDING
        round trip). This instance's own reads/acks/claims invalidate
        the cache, so the staleness window only covers OTHER replicas'
        activity: their reads move entries from backlog into the PEL (a
        stale low count overestimates backlog — errs toward shedding),
        their acks shrink XLEN and PEL together (the derived backlog
        clamps at 0 — errs toward flushing). Neither direction parks
        records, and both converge within the window."""
        key = (stream, group)
        now = time.monotonic()
        hit = self._pending_cache.get(key)
        if hit is not None and now - hit[0] < self.pending_cache_s:
            return hit[1]
        n = sum(self.xpending(stream, group).values())
        self._pending_cache[key] = (now, n)
        return n

    def backlog_len(self, stream: str, group: Optional[str] = None) -> int:
        """Undelivered backlog: XLEN minus the group's PEL (on real
        Redis, delivered-but-unacked entries still count in XLEN)."""
        n = int(self._call(self._r.xlen, stream))
        if group:
            n -= self.pending_len(stream, group)
        return max(n, 0)

    @staticmethod
    def _as_text(v) -> str:
        return v.decode() if isinstance(v, bytes) else str(v)

    # -- fleet key-value (replica heartbeats, serving/fleet.py) -------------
    def fleet_set(self, stream: str, consumer: str, payload: str) -> None:
        self._call(self._r.hset, f"fleet:{stream}",
                   mapping={consumer: payload})

    def fleet_all(self, stream: str) -> Dict[str, str]:
        vals = self._call(self._r.hgetall, f"fleet:{stream}")
        return {self._as_text(k): self._as_text(v)
                for k, v in (vals or {}).items()}

    def fleet_del(self, stream: str, consumer: str) -> None:
        self._call(self._r.hdel, f"fleet:{stream}", consumer)

    @staticmethod
    def _decode_fields(fields: Dict[bytes, bytes]) -> dict:
        """Field decode for stream entries / result hashes: keys are
        always text; payload fields stay bytes (see ``_BINARY_FIELDS``)."""
        out = {}
        for k, v in fields.items():
            key = k.decode()
            out[key] = v if key in _BINARY_FIELDS else v.decode()
        return out

    def stream_len(self, stream: str) -> int:
        return int(self._call(self._r.xlen, stream))

    def set_result(self, uri: str, fields: dict) -> None:
        self._call(self._r.hset, f"result:{uri}", mapping=fields)

    def set_results(self, results: Dict[str, dict]) -> None:
        """Batched result publish: ONE pipelined round trip for the whole
        batch (both redis-py and the in-repo RESP client expose the
        ``pipeline()`` surface) instead of one HSET round trip per
        record — the async publisher's write path."""
        if not results:
            return
        pipe = self._r.pipeline()
        for uri, fields in results.items():
            pipe.hset(f"result:{uri}", mapping=fields)
        self._call(pipe.execute)

    def pop_result(self, uri: str,
                   timeout: Optional[float] = None) -> Optional[dict]:
        timeout = self.default_timeout if timeout is None else timeout
        key = f"result:{uri}"
        found: List[Dict[bytes, bytes]] = []

        def check() -> bool:
            vals = self._call(self._r.hgetall, key)
            if vals:
                found.append(vals)
                return True
            return False

        if not self.poll_policy.wait_for(check, timeout=timeout):
            return None
        self._call(self._r.delete, key)
        return self._decode_fields(found[0])

    def pop_all_results(self) -> Dict[str, dict]:
        out = {}
        for key in self._call(self._r.keys, "result:*"):
            uri = key.decode().split(":", 1)[1]
            res = self.pop_result(uri, timeout=0)
            if res is not None:
                out[uri] = res
        return out
