"""Cluster Serving client — ``InputQueue`` / ``OutputQueue`` parity with
``pyzoo/zoo/serving/client.py:58-142``, ndarray-native instead of
image-file-native: any tensor model serves, not just jpeg classifiers.

Wire formats (``docs/guides/SERVING.md``):

* **v2 (current)** — raw little-endian tensor bytes in ``data`` plus
  explicit self-describing ``dtype`` / ``shape`` / ``v`` fields.  Encode
  is ONE memcpy (``tobytes``); decode is a zero-copy ``np.frombuffer``
  view over the wire bytes.  Both queue backends carry the ``data`` /
  ``value`` fields as binary (Redis streams and hashes are binary-safe),
  so no base64 inflation and no ``.npy`` header parse on the hot path.
* **v1 (legacy)** — base64-wrapped ``.npy`` bytes in ``data`` alone.
  :func:`decode_payload` falls back to it transparently (no ``dtype`` /
  ``shape`` fields present), and the server answers a v1 request in v1,
  so old producers AND old consumers keep working against a new server.
"""

from __future__ import annotations

import base64
import io
from typing import Dict, Optional

import numpy as np

from ..observability import new_trace_id
from .backend import LocalBackend, default_backend

INPUT_STREAM = "tensor_stream"

#: wire-format version stamped into v2 records; detection keys off the
#: ``dtype``/``shape`` fields (a v1 record has neither), the ``v`` field
#: is there for humans reading a stream dump and for future versions
WIRE_VERSION = "2"

#: hard ceiling on the payload bytes (and on any single dimension) a v2
#: header may describe — headers are attacker-controlled strings, and
#: arrays get allocated from them, so a bound must hold BEFORE anything
#: is allocated; 2 GiB is far above any real serving tensor (the server
#: additionally bounds its batch-arena preallocation, which multiplies
#: the row size by ``batch_size``)
MAX_PAYLOAD_BYTES = 1 << 31

#: ceiling on the number of dimensions a v2 header may describe — numpy
#: refuses ndarrays beyond 64 dims, and the server's batch arena (and
#: the ragged one-by-one path) prepend a batch dimension, so an
#: unbounded ndim would turn np.empty/reshape into a loop-killing raise;
#: 32 is far above any real tensor rank
MAX_DIMS = 32

__all__ = ["InputQueue", "OutputQueue", "ServingError", "encode_array",
           "decode_array", "encode_tensor", "decode_payload", "is_v2",
           "validate_v2", "new_trace_id", "WIRE_VERSION",
           "MAX_PAYLOAD_BYTES", "MAX_DIMS"]


class ServingError(RuntimeError):
    """The server wrote an error record for this uri (failed inference or
    undecodable request payload)."""


# ---------------------------------------------------------------------------
# v1 codec (legacy): base64-wrapped .npy string
# ---------------------------------------------------------------------------

def encode_array(arr: np.ndarray) -> str:
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
    return base64.b64encode(buf.getvalue()).decode("ascii")


def _check_header_bounds(dt: np.dtype, shape) -> int:
    """Shared bound checks for ANY attacker-controlled tensor header —
    v2 ``dtype``/``shape`` fields or a v1 ``.npy`` header: every
    dimension in range, rank capped at :data:`MAX_DIMS`, total bytes
    capped at :data:`MAX_PAYLOAD_BYTES`. Returns the expected payload
    byte count, computed with Python ints — ``np.prod`` would wrap
    silently on overflow, and a wrapped 0 validates a huge-shape header
    against an empty payload."""
    if len(shape) > MAX_DIMS:
        raise ValueError(
            f"tensor header has {len(shape)} dimensions (max {MAX_DIMS})")
    expect = dt.itemsize
    for d in shape:
        if d < 0 or d > MAX_PAYLOAD_BYTES:
            raise ValueError(f"tensor shape {tuple(shape)} has an "
                             f"out-of-range dimension {d}")
        expect *= d
    if expect > MAX_PAYLOAD_BYTES:
        raise ValueError(f"tensor header describes {expect} payload "
                         f"bytes (max {MAX_PAYLOAD_BYTES})")
    return expect


def decode_array(payload) -> np.ndarray:
    # b64decode accepts str or bytes — a binary-safe backend hands the
    # legacy field back as bytes, a text transport as str
    buf = io.BytesIO(base64.b64decode(payload))
    # the .npy header is attacker-controlled like a v2 header, and
    # np.load preallocates the FULL array from it before reading any
    # payload bytes — bound it the same way first (tiny records
    # claiming multi-GiB shapes are a memory-pressure DoS otherwise)
    version = np.lib.format.read_magic(buf)
    read_header = getattr(
        np.lib.format, "read_array_header_%d_%d" % version, None)
    if read_header is not None:
        shape, _, dt = read_header(buf)
    else:
        # no public reader for this version (3.0: utf-8 field names);
        # np.load accepts it, so the bounds check must too
        shape, _, dt = np.lib.format._read_array_header(buf, version)
    expect = _check_header_bounds(np.dtype(dt), shape)
    present = buf.getbuffer().nbytes - buf.tell()
    if present != expect:
        # np.load would preallocate the CLAIMED size before noticing the
        # payload is short — a 100-byte record claiming a (capped but
        # still multi-GiB) shape must be rejected before any allocation
        raise ValueError(f".npy payload is {present} bytes but its "
                         f"header claims {expect}")
    buf.seek(0)
    return np.load(buf, allow_pickle=False)


# ---------------------------------------------------------------------------
# v2 codec: raw little-endian bytes + dtype/shape fields
# ---------------------------------------------------------------------------

def encode_tensor(arr: np.ndarray, key: str = "data") -> Dict[str, object]:
    """Wire-format v2 fields for one tensor: ``{key: <raw bytes>,
    "dtype": "<f4", "shape": "3,224,224", "v": "2"}``.

    Bytes are C-contiguous little-endian (big-endian inputs are byte-
    swapped once here so the decode side is always a straight view);
    ``dtype`` is the numpy dtype spec string, ``shape`` comma-joined.
    ``key`` selects the payload field name — ``data`` on the request
    stream, ``value`` on result hashes."""
    a = np.asarray(arr)
    if not a.flags.c_contiguous:
        # ascontiguousarray unconditionally would also promote 0-d
        # arrays to 1-d and lose the scalar shape on the wire
        a = np.ascontiguousarray(a)
    if a.dtype.hasobject:
        raise ValueError(
            f"cannot encode dtype {a.dtype} — object arrays have no raw "
            f"byte representation (and never decoded under v1 either: "
            f"np.save(allow_pickle=False) rejects them)")
    if a.dtype.byteorder == ">":
        a = a.astype(a.dtype.newbyteorder("<"))
    return {key: a.tobytes(), "dtype": a.dtype.str,
            "shape": ",".join(str(d) for d in a.shape), "v": WIRE_VERSION}


def is_v2(fields: Dict) -> bool:
    """True when a stream/result record carries the v2 header fields."""
    return "dtype" in fields and "shape" in fields


def parse_v2_header(fields: Dict):
    """``(np.dtype, shape_tuple, payload_bytes)`` from a v2 record's
    header fields. Raises on malformed specs, including any dimension
    that is negative or above :data:`MAX_PAYLOAD_BYTES` — np.empty on
    such a shape raises (or allocates absurdly), and the server's arena
    path relies on a validated header never failing allocation."""
    dt = np.dtype(str(fields["dtype"]))
    if dt.subdtype is not None:
        # "(2,2)<f4" would smuggle extra dims past every shape check:
        # frombuffer expands them and the reshape/arena paths blow up
        raise ValueError(
            f"v2 dtype {fields['dtype']!r} is a subarray dtype — dims "
            f"belong in the shape field")
    shape = tuple(int(s) for s in str(fields["shape"]).split(",") if s)
    return dt, shape, _check_header_bounds(dt, shape)


def validate_v2(fields: Dict, key: str = "data"):
    """Fully validate a v2 record WITHOUT touching the payload bytes:
    ``(payload_bytes, np.dtype, shape)``. Parses the header, normalizes a
    text-transport payload, and rejects dtypes with no raw byte
    representation (object, zero-itemsize flexible types) and
    header/byte-length mismatches — after this, ``np.frombuffer`` cannot
    fail. The ONE definition of what the wire accepts: both
    :func:`decode_payload` and the server's cheap pre-copy check use it,
    so the accept rule cannot diverge between client and server."""
    dt, shape, expect = parse_v2_header(fields)
    if dt.hasobject or dt.itemsize == 0:
        raise ValueError(
            f"v2 dtype {dt.str} has no raw byte representation")
    payload = fields[key]
    if isinstance(payload, str):
        # a text-only transport: latin-1 is the lossless byte<->str map
        payload = payload.encode("latin-1")
    if len(payload) != expect:
        raise ValueError(
            f"v2 payload is {len(payload)} bytes but dtype={dt.str} "
            f"shape={shape} needs {expect}")
    return payload, dt, shape


def decode_payload(fields: Dict, key: str = "data") -> np.ndarray:
    """Decode one record's tensor payload, v2 or v1.

    v2 (``dtype``/``shape`` fields present) returns a ZERO-COPY read-only
    ``np.frombuffer`` view over the wire bytes; v1 falls back to the
    base64 ``.npy`` decode. Raises on malformed payloads (bad base64,
    unrepresentable dtype, header/byte-length mismatch) — the server
    converts that into an addressable error record."""
    if is_v2(fields):
        payload, dt, shape = validate_v2(fields, key)
        return np.frombuffer(payload, dtype=dt).reshape(shape)
    return decode_array(fields[key])


class InputQueue:
    """Producer side: ``enqueue(uri, tensor)``. Blocks (up to ``timeout``)
    when the stream is at capacity — the backpressure the reference
    implements by polling Redis used_memory against a threshold.

    With ``fleet_backpressure`` on (or conf
    ``zoo.serving.fleet_backpressure``), enqueue additionally consults
    the fleet registry (``serving/fleet.py``; cached, bounded
    staleness): when EVERY live replica reports itself saturated the
    producer is first slowed (a backed-off wait up to
    ``fleet_wait_s``) and then refused with
    :class:`~analytics_zoo_tpu.serving.fleet.FleetSaturatedError` —
    coordinated, fleet-level backpressure upstream of the stream, so
    individual replicas' load shedding becomes the backstop rather
    than the first line of defense."""

    def __init__(self, backend: Optional[LocalBackend] = None,
                 stream: str = INPUT_STREAM, timeout: float = 30.0,
                 fleet_backpressure: Optional[bool] = None,
                 fleet_wait_s: float = 1.0,
                 fleet_view=None):
        self.backend = backend if backend is not None else default_backend()
        self.stream = stream
        self.timeout = timeout
        if fleet_backpressure is None:
            from ..common.context import get_zoo_context
            fleet_backpressure = bool(get_zoo_context().get(
                "zoo.serving.fleet_backpressure", False))
        self.fleet_backpressure = bool(fleet_backpressure)
        self.fleet_wait_s = float(fleet_wait_s)
        self._fleet_view = fleet_view
        if self.fleet_backpressure and self._fleet_view is None:
            from .fleet import FleetView
            self._fleet_view = FleetView(self.backend, self.stream)

    def _check_fleet(self) -> None:
        """Slow, then refuse: wait (backed off) up to ``fleet_wait_s``
        for the fleet to report headroom; raise once the budget is
        spent. The cached view bounds the backend reads underneath."""
        if not self.fleet_backpressure or self._fleet_view is None:
            return
        if not self._fleet_view.saturated():
            return
        from ..common.reliability import RetryPolicy
        from .fleet import FleetSaturatedError
        wait = RetryPolicy(base_delay=0.02, max_delay=0.2)
        if not wait.wait_for(lambda: not self._fleet_view.saturated(),
                             timeout=self.fleet_wait_s):
            raise FleetSaturatedError(
                f"fleet serving stream {self.stream!r} is saturated "
                f"(every live replica above its shed watermark for "
                f"{self.fleet_wait_s:.1f}s); enqueue refused — retry "
                f"with backoff or scale the fleet")

    def enqueue(self, uri: str, data: np.ndarray,
                trace: Optional[str] = None,
                deadline_ms: Optional[int] = None,
                model: Optional[str] = None) -> str:
        """Enqueue one record (wire-format v2: raw bytes + dtype/shape).
        Every record is stamped with a Dapper-style ``trace`` id (16 hex
        chars; pass ``trace=`` to adopt a caller's id, e.g. an upstream
        request id) — the serve loop carries it through batch assembly,
        dispatch, and publish, emitting per-request phase events under
        that id so the JSON event log holds each request's exact latency
        breakdown. Records enqueued by foreign producers without the
        field still serve; they just have no trace.

        ``deadline_ms`` stamps an ABSOLUTE epoch-millisecond deadline
        (the clock the stream entry ids already share): a server reading
        the record after it has passed answers a distinct ``deadline
        exceeded`` error instead of spending dispatch on a request whose
        caller has already timed out. Producers typically stamp
        ``int(time.time() * 1000) + budget_ms``. No stamp = no deadline
        (the pre-deadline contract, unchanged).

        ``model`` routes the record to one named lane of a multiplexed
        server (several models on one stream — ``ClusterServing`` with a
        ``{name: model}`` dict). No stamp = the server's primary lane; a
        name the server does not host is answered with a distinct
        ``unknown model`` error rather than dispatched anywhere."""
        self._check_fleet()
        fields = encode_tensor(np.asarray(data))
        fields["uri"] = uri
        # falsy trace ("" from an unset upstream header) mints too —
        # stamping "" would merge unrelated requests into one bogus trace
        fields["trace"] = trace or new_trace_id()
        if deadline_ms is not None:
            fields["deadline_ms"] = str(int(deadline_ms))
        if model:
            fields["model"] = str(model)
        return self.backend.xadd(self.stream, fields, timeout=self.timeout)


class OutputQueue:
    """Consumer side: ``query(uri)`` one result (raises ``ServingError`` if
    the server recorded a failure for that uri), ``dequeue()`` everything
    successful (failures land in ``last_errors``, they never crash the
    drain or lose other clients' results). Results decode via
    :func:`decode_payload` — v2 values come back as zero-copy read-only
    views over the result bytes; copy before mutating in place."""

    def __init__(self, backend: Optional[LocalBackend] = None):
        self.backend = backend if backend is not None else default_backend()
        self.last_errors: Dict[str, str] = {}

    def query(self, uri: str, timeout: Optional[float] = None
              ) -> Optional[np.ndarray]:
        res = self.backend.pop_result(uri, timeout=timeout)
        if res is None:
            return None
        if "value" not in res:
            raise ServingError(f"{uri}: {res.get('error', 'unknown error')}")
        return decode_payload(res, "value")

    def dequeue(self) -> Dict[str, np.ndarray]:
        out: Dict[str, np.ndarray] = {}
        self.last_errors = {}
        for uri, res in self.backend.pop_all_results().items():
            if "value" in res:
                out[uri] = decode_payload(res, "value")
            else:
                self.last_errors[uri] = res.get("error", "unknown error")
        return out
