"""Cluster Serving client — ``InputQueue`` / ``OutputQueue`` parity with
``pyzoo/zoo/serving/client.py:58-142``, ndarray-native instead of
image-file-native: payloads are base64-wrapped ``.npy`` bytes (dtype+shape
self-describing), so any tensor model serves, not just jpeg classifiers.
"""

from __future__ import annotations

import base64
import io
from typing import Dict, Optional

import numpy as np

from ..observability import new_trace_id
from .backend import LocalBackend, default_backend

INPUT_STREAM = "tensor_stream"

__all__ = ["InputQueue", "OutputQueue", "ServingError", "encode_array",
           "decode_array", "new_trace_id"]


class ServingError(RuntimeError):
    """The server wrote an error record for this uri (failed inference or
    undecodable request payload)."""


def encode_array(arr: np.ndarray) -> str:
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
    return base64.b64encode(buf.getvalue()).decode("ascii")


def decode_array(payload: str) -> np.ndarray:
    return np.load(io.BytesIO(base64.b64decode(payload)),
                   allow_pickle=False)


class InputQueue:
    """Producer side: ``enqueue(uri, tensor)``. Blocks (up to ``timeout``)
    when the stream is at capacity — the backpressure the reference
    implements by polling Redis used_memory against a threshold."""

    def __init__(self, backend: Optional[LocalBackend] = None,
                 stream: str = INPUT_STREAM, timeout: float = 30.0):
        self.backend = backend if backend is not None else default_backend()
        self.stream = stream
        self.timeout = timeout

    def enqueue(self, uri: str, data: np.ndarray,
                trace: Optional[str] = None) -> str:
        """Enqueue one record. Every record is stamped with a Dapper-style
        ``trace`` id (16 hex chars; pass ``trace=`` to adopt a caller's
        id, e.g. an upstream request id) — the serve loop carries it
        through batch assembly, dispatch, and publish, emitting
        per-request phase events under that id so the JSON event log
        holds each request's exact latency breakdown. Records enqueued by
        foreign producers without the field still serve; they just have
        no trace."""
        # falsy trace ("" from an unset upstream header) mints too —
        # stamping "" would merge unrelated requests into one bogus trace
        return self.backend.xadd(
            self.stream, {"uri": uri, "data": encode_array(np.asarray(data)),
                          "trace": trace or new_trace_id()},
            timeout=self.timeout)


class OutputQueue:
    """Consumer side: ``query(uri)`` one result (raises ``ServingError`` if
    the server recorded a failure for that uri), ``dequeue()`` everything
    successful (failures land in ``last_errors``, they never crash the
    drain or lose other clients' results)."""

    def __init__(self, backend: Optional[LocalBackend] = None):
        self.backend = backend if backend is not None else default_backend()
        self.last_errors: Dict[str, str] = {}

    def query(self, uri: str, timeout: Optional[float] = None
              ) -> Optional[np.ndarray]:
        res = self.backend.pop_result(uri, timeout=timeout)
        if res is None:
            return None
        if "value" not in res:
            raise ServingError(f"{uri}: {res.get('error', 'unknown error')}")
        return decode_array(res["value"])

    def dequeue(self) -> Dict[str, np.ndarray]:
        out: Dict[str, np.ndarray] = {}
        self.last_errors = {}
        for uri, res in self.backend.pop_all_results().items():
            if "value" in res:
                out[uri] = decode_array(res["value"])
            else:
                self.last_errors[uri] = res.get("error", "unknown error")
        return out
