"""Durable dead-letter queue — spill-to-disk for records serving cannot
answer, plus the operator replay path (``scripts/zoo-dlq``).

PR 5 gave dead-lettered records an *addressable error* so producers fail
fast; the work itself was still lost — a poison record or a result-store
outage discarded the request payload forever. This module makes
dead-lettering durable: the serve loop and the publisher spill the full
request (uri, trace, reason, and the wire-format v2 tensor payload) to an
append-only on-disk queue, and an operator replays it onto the input
stream after the outage clears.

On-disk format (the checkpoint subsystem's commit idioms, applied to an
append-only log — ``utils/checkpoint.py`` is the sibling):

* one directory per queue; records append to **segments** named
  ``dlq-<epoch_ms>-<seq>.jsonl`` (``.open`` suffix while the writer owns
  it; sealed — atomically renamed — on rotation/close, so a reader can
  tell "the server may still be appending" from "safe to replay"),
* each line is **CRC-framed**: ``<crc32 hex8> <json>`` with the checksum
  over the JSON bytes — a torn tail write (the crash shape for appends)
  fails its frame and is skipped + counted, never parsed as garbage,
* appends are **fsynced** — a record the server acknowledged as
  dead-lettered survives the process,
* total on-disk bytes are **bounded** (``max_bytes``): once exceeded the
  oldest non-active segment is evicted (``.replayed`` leftovers first —
  they are receipts, not work), counting every dropped record in
  ``zoo_serving_dlq_evicted_total``. A bounded DLQ loses the *oldest*
  dead letters under sustained overflow and says so in a counter; an
  unbounded one silently eats the disk and takes the whole host down.

Replay is **at-most-once** per segment: the segment is renamed to
``*.replayed`` *before* any record is re-enqueued (the rename is the
commit marker, exactly like the checkpoint manifest) — a crash mid-replay
leaves part of the segment unserved, never served twice. Re-enqueued
records carry **fresh trace ids**; the original id is preserved as
``replay_of`` so the event log links the two lifetimes.

Metrics (``docs/guides/OBSERVABILITY.md``): ``zoo_serving_dlq_records`` /
``zoo_serving_dlq_bytes`` gauges (depth = replayable records),
``zoo_serving_dlq_spilled_total{reason=}``,
``zoo_serving_dlq_evicted_total``, ``zoo_serving_dlq_corrupt_total``,
``zoo_serving_dlq_replayed_total``.

Nothing here imports jax — the operator CLI lists/replays from any host.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import threading
import time
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..observability import default_registry, new_trace_id
from .client import INPUT_STREAM, encode_tensor

log = logging.getLogger("analytics_zoo_tpu.serving.dlq")

__all__ = ["DeadLetterQueue", "SEGMENT_PREFIX"]

SEGMENT_PREFIX = "dlq-"
_OPEN, _SEALED, _REPLAYED = "open", "sealed", "replayed"

#: sort/evict/replay order is the segment's name (epoch ms + a process
#: sequence number) — append order, oldest first
_SUFFIXES = {".jsonl.open": _OPEN, ".jsonl.replayed": _REPLAYED,
             ".jsonl": _SEALED}


def _segment_state(name: str) -> Optional[str]:
    if not name.startswith(SEGMENT_PREFIX):
        return None
    for suffix, state in _SUFFIXES.items():
        if name.endswith(suffix):
            return state
    return None


def _base_name(name: str) -> str:
    """Segment identity independent of its lifecycle suffix."""
    for suffix in (".open", ".replayed"):
        if name.endswith(suffix):
            return name[:-len(suffix)]
    return name


class DeadLetterQueue:
    """One durable dead-letter directory: thread-safe appends from the
    serve loop and the publisher, segment lifecycle (open → sealed →
    replayed), bounded total bytes, and the replay/purge surface the
    ``zoo-dlq`` CLI wraps."""

    def __init__(self, directory: str, max_bytes: int = 64 << 20,
                 segment_bytes: int = 8 << 20, registry=None,
                 fsync: bool = True):
        if max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1 ({max_bytes})")
        self.directory = directory
        self.max_bytes = int(max_bytes)
        self.segment_bytes = int(segment_bytes)
        self.fsync = bool(fsync)
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._seq = 0
        self._active: Optional[str] = None      # active segment file name
        self._active_f = None
        self._active_bytes = 0
        self._active_records = 0
        m = registry if registry is not None else default_registry()
        self.metrics = m
        self._m_records = m.gauge(
            "zoo_serving_dlq_records",
            "replayable dead-lettered records on disk (open + sealed "
            "segments)")
        self._m_bytes = m.gauge(
            "zoo_serving_dlq_bytes",
            "total dead-letter-queue bytes on disk, replayed receipts "
            "included")
        self._m_evicted = m.counter(
            "zoo_serving_dlq_evicted_total",
            "dead-lettered records dropped by oldest-segment eviction "
            "(the DLQ hit its disk bound)")
        self._m_corrupt = m.counter(
            "zoo_serving_dlq_corrupt_total",
            "DLQ lines skipped for a CRC/JSON frame failure (torn tail "
            "writes)")
        self._m_replayed = m.counter(
            "zoo_serving_dlq_replayed_total",
            "dead-lettered records re-enqueued onto the input stream")
        # the dead-letter reasons are a closed label set; pre-creating
        # the counters keeps the spill path allocation-free and the
        # label values statically enumerable (zoolint ZL015). "other"
        # absorbs foreign reason strings a future caller might pass —
        # never misattributed to a real category, and the spilled
        # record itself keeps the exact string
        self._spilled = {
            reason: m.counter(
                "zoo_serving_dlq_spilled_total",
                "records spilled to the on-disk dead-letter queue, by "
                "dead-letter reason",
                labels={"reason": reason})
            for reason in ("dispatch", "publish", "other")}
        # incrementally-maintained totals: the append path must stay
        # O(1) — a full directory rescan per spill would go quadratic
        # during the very outage the DLQ exists to absorb. One scan at
        # construction seeds them; append/evict/replay/purge adjust.
        # They are PER-INSTANCE: a zoo-dlq CLI mutating this directory
        # from another process is folded back in lazily — the byte total
        # re-seeds from the filesystem before any eviction decision
        # (never evict on a phantom count), and the record gauge
        # self-corrects at the next construction/replay of this handle.
        self._disk_bytes = 0
        self._replayable = 0
        for s in self.segments():
            self._disk_bytes += s["bytes"]
            if s["state"] != _REPLAYED:
                self._replayable += s["records"]
        self._refresh_gauges()

    # -- survey --------------------------------------------------------------
    def segments(self) -> List[Dict[str, object]]:
        """Oldest-first inventory: ``{"name", "state", "bytes",
        "records", "corrupt"}`` per segment. Counting records reads each
        file once — cheap for an operator surface, not a hot path."""
        out = []
        for name in sorted(os.listdir(self.directory), key=_base_name):
            state = _segment_state(name)
            if state is None:
                continue
            path = os.path.join(self.directory, name)
            records = corrupt = 0
            for rec in self._scan_file(path, count_corrupt=False):
                if rec is None:
                    corrupt += 1
                else:
                    records += 1
            out.append({"name": name, "state": state,
                        "bytes": os.path.getsize(path),
                        "records": records, "corrupt": corrupt})
        return out

    @property
    def depth(self) -> int:
        """Replayable records (open + sealed segments)."""
        return sum(s["records"] for s in self.segments()
                   if s["state"] != _REPLAYED)

    @property
    def total_bytes(self) -> int:
        return sum(s["bytes"] for s in self.segments())

    # -- append --------------------------------------------------------------
    def append(self, uri: str, tensor, reason: str,
               trace: Optional[str] = None,
               error: Optional[str] = None,
               model: Optional[str] = None) -> None:
        """Spill one dead-lettered record durably. ``tensor`` is the
        original request payload (any ndarray-like); ``reason`` labels
        the spill counter (``dispatch`` / ``publish``); ``model`` is the
        lane the record was routed to on a multiplexed server — replay
        re-stamps it so the record goes back to the SAME model. Raises
        on an unwritable directory — the CALLER decides whether losing
        the record is acceptable (the serve loop logs and answers the
        producer either way)."""
        fields = encode_tensor(np.asarray(tensor))
        rec = {
            "uri": uri,
            "trace": trace,
            "reason": reason,
            "error": error,
            "ts_ms": int(time.time() * 1000),
            "data": base64.b64encode(fields["data"]).decode("ascii"),
            "dtype": fields["dtype"],
            "shape": fields["shape"],
            "v": fields["v"],
        }
        if model:
            rec["model"] = str(model)
        payload = json.dumps(rec, separators=(",", ":")).encode("utf-8")
        line = b"%08x " % (zlib.crc32(payload) & 0xFFFFFFFF) + payload + b"\n"
        with self._lock:
            f = self._writer(len(line))
            f.write(line)
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
            self._active_bytes += len(line)
            self._active_records += 1
            self._disk_bytes += len(line)
            self._replayable += 1
            if self._disk_bytes > self.max_bytes:
                self._evict_over_bound()
        self._spilled.get(reason, self._spilled["other"]).inc()
        self._refresh_gauges()
        self.metrics.emit("serving.dlq_spill", uri=uri, trace=trace,
                          reason=reason, error=error)

    def _writer(self, incoming: int):
        """The active segment's file handle, rotating first when the
        incoming line would push it past ``segment_bytes``. Call under
        the lock."""
        if (self._active_f is not None
                and self._active_bytes + incoming > self.segment_bytes):
            self._seal_active_locked()
        if self._active_f is None:
            self._seq += 1
            name = (f"{SEGMENT_PREFIX}{int(time.time() * 1000)}"
                    f"-{self._seq:04d}.jsonl.open")
            self._active = name
            self._active_f = open(os.path.join(self.directory, name), "ab")
            self._active_bytes = 0
            self._active_records = 0
        return self._active_f

    def _seal_active_locked(self) -> None:
        """open → sealed: close the handle and atomically drop the
        ``.open`` suffix — the rename publishes "no writer owns this
        segment anymore" to replaying readers."""
        if self._active_f is None:
            return
        self._active_f.close()
        path = os.path.join(self.directory, self._active)
        os.replace(path, path[:-len(".open")])
        self._active = None
        self._active_f = None
        self._active_bytes = 0
        self._active_records = 0

    def _evict_over_bound(self) -> None:
        """Drop oldest non-active segments until the directory fits
        ``max_bytes``: ``.replayed`` receipts first (they hold no work),
        then the oldest sealed work. Call under the lock; the append
        path only enters here once ``_disk_bytes`` crossed the bound, so
        the directory walk is paid on overflow, never per spill.

        The walk also RE-SEEDS ``_disk_bytes`` from the filesystem
        before deciding anything: the ``zoo-dlq`` CLI may have replayed
        or purged segments out from under this instance's incremental
        counter, and evicting live work off a phantom total would
        destroy exactly the dead letters the bound exists to protect."""
        entries = []
        fresh_bytes = self._active_bytes if self._active_f is not None else 0
        for name in os.listdir(self.directory):
            state = _segment_state(name)
            if state is None:
                continue
            size = os.path.getsize(os.path.join(self.directory, name))
            fresh_bytes += 0 if name == self._active else size
            if state == _OPEN or name == self._active:
                # a foreign live writer may own a non-active .open (two
                # servers sharing a DLQ dir is a misconfiguration, but
                # unlinking its inode would silently swallow its future
                # spills) — leave it; the bytes gauge shows the overshoot
                continue
            entries.append((state != _REPLAYED, _base_name(name),
                            name, size, state))
        self._disk_bytes = fresh_bytes
        entries.sort()      # replayed receipts first, then oldest work
        for _work, _base, name, size, state in entries:
            if self._disk_bytes <= self.max_bytes:
                break
            path = os.path.join(self.directory, name)
            dropped = 0
            if state != _REPLAYED:
                dropped = sum(1 for r in self._scan_file(
                    path, count_corrupt=False) if r is not None)
                log.warning("DLQ over its %d-byte bound; evicting oldest "
                            "segment %s (%d records lost)", self.max_bytes,
                            name, dropped)
                self._m_evicted.inc(dropped)
            os.unlink(path)
            self._disk_bytes -= size
            self._replayable -= dropped
            if dropped:
                self.metrics.emit("serving.dlq_evict", segment=name,
                                  records=dropped)

    def _refresh_gauges(self) -> None:
        self._m_records.set(max(self._replayable, 0))
        self._m_bytes.set(max(self._disk_bytes, 0))

    # -- read ----------------------------------------------------------------
    def _scan_file(self, path: str,
                   count_corrupt: bool = True) -> Iterator[Optional[dict]]:
        """Yield each frame's record dict, or None for a line that fails
        its CRC/JSON frame (torn tail append)."""
        try:
            with open(path, "rb") as f:
                for raw in f:
                    rec = self._parse_line(raw)
                    if rec is None and count_corrupt:
                        self._m_corrupt.inc()
                    yield rec
        except FileNotFoundError:
            return

    @staticmethod
    def _parse_line(raw: bytes) -> Optional[dict]:
        raw = raw.rstrip(b"\n")
        if not raw:
            return None
        try:
            crc_hex, payload = raw.split(b" ", 1)
            if int(crc_hex, 16) != zlib.crc32(payload) & 0xFFFFFFFF:
                return None
            return json.loads(payload)
        except (ValueError, json.JSONDecodeError):
            return None

    def scan(self, segment: Optional[str] = None,
             include_replayed: bool = False
             ) -> Iterator[Tuple[str, dict]]:
        """Yield ``(segment_name, record)`` oldest-first across the
        queue (or one ``segment``). Corrupt frames are counted and
        skipped."""
        for s in self.segments():
            if segment is not None and s["name"] != segment \
                    and _base_name(s["name"]) != _base_name(segment):
                continue
            if s["state"] == _REPLAYED and not include_replayed:
                continue
            path = os.path.join(self.directory, s["name"])
            for rec in self._scan_file(path):
                if rec is not None:
                    yield s["name"], rec

    # -- replay / purge ------------------------------------------------------
    def replay(self, backend, stream: str = INPUT_STREAM,
               segment: Optional[str] = None,
               uris: Optional[List[str]] = None,
               include_open: bool = False,
               rate: Optional[float] = None,
               sleep=time.sleep) -> int:
        """Re-enqueue dead-lettered records onto the input stream with
        FRESH trace ids (``replay_of`` carries the original id so the
        event log links both lifetimes). At-most-once: each segment is
        renamed ``*.replayed`` BEFORE its first record is re-enqueued —
        a crash mid-replay under-delivers, never double-delivers.

        ``rate`` (records/second, ``zoo-dlq replay --rate N``) paces the
        re-enqueues on a fixed schedule (record i is enqueued no earlier
        than ``i/rate`` seconds after the first) so a large replay
        cannot itself stand the backlog above the server's shed
        watermark and re-dead-letter the very records being recovered.
        Unpaced replay (the default) is the drain-at-full-speed mode
        for a server with shedding off or ample headroom.

        This instance's OWN active segment is sealed first (it holds the
        writer, so that is always safe); other ``.open`` segments on
        disk belong to some other process's writer and are skipped
        unless ``include_open`` (which seals them too — only safe when
        the owning server is stopped; the CLI makes the operator say so
        explicitly). A ``uris`` filter
        re-enqueues only matching records but still retires the whole
        segment — the skipped records are abandoned, and the count is
        logged loudly. Returns the number of records re-enqueued."""
        if rate is not None and rate <= 0:
            # validated before ANY side effect: sealing/renaming happens
            # below, and a rejected argument must leave the directory
            # exactly as it found it
            raise ValueError(f"replay rate must be > 0 records/s ({rate})")
        with self._lock:
            self._seal_active_locked()
            targets = []
            for s in self.segments():
                if segment is not None \
                        and _base_name(s["name"]) != _base_name(segment):
                    continue
                if s["state"] == _REPLAYED:
                    continue
                if s["state"] == _OPEN:
                    if not include_open:
                        log.warning("skipping open segment %s (a live "
                                    "server may still be appending; pass "
                                    "include_open once it is stopped)",
                                    s["name"])
                        continue
                    path = os.path.join(self.directory, s["name"])
                    sealed = path[:-len(".open")]
                    os.replace(path, sealed)
                    s = dict(s, name=os.path.basename(sealed))
                targets.append(s["name"])
        replayed = skipped = 0
        t0 = time.monotonic()
        for name in targets:
            path = os.path.join(self.directory, name)
            done = path + ".replayed"
            # the commit marker: rename BEFORE any re-enqueue
            os.replace(path, done)
            for rec in self._scan_file(done):
                if rec is None:
                    continue
                with self._lock:
                    self._replayable -= 1   # retired, replayed or not
                if uris is not None and rec.get("uri") not in uris:
                    skipped += 1
                    continue
                fields = {
                    "data": base64.b64decode(rec["data"]),
                    "dtype": rec["dtype"],
                    "shape": rec["shape"],
                    "v": rec.get("v", "2"),
                    "uri": rec["uri"],
                    "trace": new_trace_id(),
                }
                if rec.get("trace"):
                    fields["replay_of"] = rec["trace"]
                if rec.get("model"):
                    # multiplexed servers route by this field: the
                    # replayed record must land on the SAME lane
                    fields["model"] = rec["model"]
                if rate is not None and replayed:
                    # fixed schedule, not inter-record gaps: a slow xadd
                    # does not compound the pace, and the total duration
                    # is deterministic at (n-1)/rate from the first send
                    due = t0 + replayed / rate
                    delay = due - time.monotonic()
                    if delay > 0:
                        sleep(delay)
                backend.xadd(stream, fields)
                replayed += 1
        if skipped:
            log.warning("replay retired %d record(s) without re-enqueueing "
                        "them (uri filter): their segments are marked "
                        ".replayed and they will never be served", skipped)
        if replayed:
            self._m_replayed.inc(replayed)
            self.metrics.emit("serving.dlq_replay", records=replayed,
                              segments=len(targets), skipped=skipped)
        self._refresh_gauges()
        return replayed

    def purge(self, replayed_only: bool = True) -> int:
        """Delete segments; by default only ``.replayed`` receipts.
        ``replayed_only=False`` deletes UNREPLAYED work too (the
        operator's explicit give-up). FOREIGN ``.open`` segments are
        never touched: another process's live writer keeps its fd, so
        an unlink would silently sink every spill it makes until its
        next rotation — not just drop existing work. Returns segments
        removed."""
        removed = 0
        with self._lock:
            if not replayed_only:
                self._seal_active_locked()
            for s in self.segments():
                if replayed_only and s["state"] != _REPLAYED:
                    continue
                if s["name"] == self._active:
                    continue
                if s["state"] == _OPEN:
                    log.warning(
                        "purge: skipping open segment %s — a live server "
                        "may own its writer (an unlinked inode would "
                        "swallow its future spills); stop the server and "
                        "replay/purge again", s["name"])
                    continue
                os.unlink(os.path.join(self.directory, s["name"]))
                removed += 1
                self._disk_bytes -= s["bytes"]
                if s["state"] != _REPLAYED:
                    self._replayable -= s["records"]
        self._refresh_gauges()
        return removed

    def close(self) -> None:
        """Seal the active segment (making it replayable) and release
        the handle. Idempotent."""
        with self._lock:
            self._seal_active_locked()
        self._refresh_gauges()
